"""Serving-knob tuner: bucket ladder x in-flight window vs an arrival
trace.

The engine's two knobs trade compile count, pad waste, and host/device
overlap: a dense ladder wastes less padding but compiles more programs
and reuses each less; a deeper in-flight window hides more host time on
an async backend but buys nothing on a synchronous one.  Neither is
predictable from first principles across backends — so, like the eval
knobs, they are *measured*: a deterministic trace of ragged batch sizes
is replayed through every (ladder, max_in_flight) candidate (grid
search — the space is tiny), each candidate's outputs are
equality-gated against the blocking ``eval_tpu`` loop on the identical
stream, and the sustained-qps winner persists in the tuning cache under
the ``serve|...`` key.

The trace can be any ``serve.loadgen`` trace (``trace=`` an ``Arrival``
list or a plain size list, or ``trace_kind="poisson"/"bursty"/
"diurnal"`` for the canonical defaults) — tune against the traffic
shape you expect; the legacy ``synthetic_trace`` remains the
compatibility default.  ``tune_router`` extends the same protocol one
level up: it races (ladder x in-flight x EWMA alpha) for the runtime
scheme router (``serve/router.py``) against a chosen trace and persists
the winner under the ``router|...`` key (``lookup_router_knobs`` reads
it back at router construction).

``ServingEngine.warmup(tune=True)`` consults the cache first and only
searches on a miss (and only when its server can mint keys — the plain
``api.DPF``); ``benchmark.py --autotune`` forces the full search.
"""

from __future__ import annotations

import time

import numpy as np

from .cache import TuningCache, default_cache
from .fingerprint import cache_key, device_fingerprint


def synthetic_trace(cap: int, batches: int = 16, seed: int = 7) -> list:
    """A deterministic ragged arrival trace: ~half full batches (the
    loaded-server regime), the rest a mix of half-size and uniform
    stragglers, so every ladder rung and the remainder path get
    exercised.  Returns a list of batch sizes in [1, cap]."""
    rng = np.random.default_rng(seed)
    sizes = []
    for _ in range(batches):
        r = rng.random()
        if r < 0.5:
            sizes.append(cap)
        elif r < 0.8:
            sizes.append(max(1, cap // 2))
        else:
            sizes.append(int(rng.integers(1, cap + 1)))
    return sizes


def resolve_trace(cap: int, trace=None, trace_kind: str | None = None,
                  trace_kw: dict | None = None) -> list:
    """The tuner's trace input, as a batch-size list.

    Exactly one source: an explicit ``trace`` (``loadgen.Arrival`` list
    or plain sizes), or a ``trace_kind`` string resolved through
    ``serve.loadgen`` (``trace_kw`` forwards to ``make_trace``; without
    it the kind's canonical default trace is used).  Neither given =
    the legacy ``synthetic_trace`` (compatibility default)."""
    from ..serve import loadgen
    if trace is not None and trace_kind is not None:
        raise ValueError("pass trace OR trace_kind, not both")
    if trace_kw and trace_kind is None:
        raise ValueError("trace_kw only parameterizes trace_kind")
    if trace_kind is not None:
        if trace_kw:
            kw = {"cap": cap, **trace_kw}
            if trace_kind == "replay":   # replay_trace takes no cap
                kw.pop("cap", None)
            trace = loadgen.make_trace(trace_kind, **kw)
        else:
            trace = loadgen.default_trace(trace_kind, cap)
    if trace is None:
        return synthetic_trace(cap)
    return loadgen.batch_sizes(trace)


def serve_shape_of(server) -> dict:
    """The cache-key shape fields of a prepared server (api.DPF or
    ShardedDPFServer).  A mesh server's shape carries its mesh split
    (``fingerprint.mesh_tag``): the batch axis changes which ladders
    even make sense, so mesh serving knobs must not be confused with
    single-device ones (``mesh_tune.tune_mesh_serving`` populates the
    mesh-tagged entries, ``lookup_serve_knobs`` reads them back
    transparently through this shape)."""
    n = getattr(server, "table_num_entries", None) or server.n
    e = (getattr(server, "table_effective_entry_size", None)
         or getattr(server, "entry_size"))
    shape = {
        "n": int(n), "entry_size": int(e),
        "prf_method": server.prf_method,
        "scheme": getattr(server, "scheme", "logn"),
        "radix": getattr(server, "radix", 2),
    }
    mesh = getattr(server, "mesh", None)
    if mesh is not None:
        from .fingerprint import mesh_tag
        shape["mesh"] = mesh_tag(mesh)
    return shape


def lookup_serve_knobs(server, cap: int,
                       cache: TuningCache | None = None) -> dict | None:
    """Tuned (buckets, max_in_flight) for this server shape, or None.
    Never raises — an unreadable cache is a miss."""
    try:
        cache = cache if cache is not None else default_cache()
        rec = cache.lookup(
            cache_key("serve", batch=cap, **serve_shape_of(server)))
        return rec.get("knobs") if rec else None
    except Exception:  # pragma: no cover — cache must never break serving
        return None


def tune_serving(dpf, *, cap: int | None = None, trace=None,
                 trace_kind: str | None = None,
                 trace_kw: dict | None = None,
                 in_flight=(1, 2, 4), ladders=None, reps: int = 2,
                 distinct: int = 16, cache: TuningCache | None = None,
                 force: bool = False, log=None) -> dict:
    """Measure (ladder, max_in_flight) candidates on ``dpf`` (a prepared
    ``api.DPF``) and persist the winner.  Returns the cache record with
    a transient ``searched`` field (False = warm cache, nothing ran).

    ``trace``/``trace_kind`` choose the replayed workload
    (``resolve_trace``): a ``serve.loadgen`` trace tunes the ladder for
    the traffic shape you expect; the default stays the legacy
    ``synthetic_trace``.  An EXPLICIT trace always re-measures: the
    cache key carries only the table shape, so a warm entry tuned on a
    different workload must not masquerade as this one's answer (the
    stored record's ``measured.trace`` says what was replayed)."""
    from ..serve.buckets import Buckets
    from ..serve.engine import ServingEngine

    cache = cache if cache is not None else default_cache()
    shape = serve_shape_of(dpf)
    cap = int(cap or min(dpf.BATCH_SIZE, 512))
    key = cache_key("serve", batch=cap, **shape)
    if not force and trace is None and trace_kind is None:
        rec = cache.lookup(key)
        if rec is not None:
            return {**rec, "searched": False}

    n = shape["n"]
    trace = resolve_trace(cap, trace, trace_kind, trace_kw)
    if max(trace) > cap:
        raise ValueError("trace batch %d exceeds cap %d"
                         % (max(trace), cap))
    ks = [dpf.gen((i * 0x9E3779B1) % n, n, seed=b"serve-tune-%d" % i)[0]
          for i in range(distinct)]
    stream = [[ks[(j + i) % distinct] for i in range(b)]
              for j, b in enumerate(trace)]
    total = sum(trace)
    # the equality-gate target: the blocking loop on the identical stream
    reference = [np.asarray(dpf.eval_tpu(b)) for b in stream]

    candidates = []
    for ladder in (ladders if ladders is not None
                   else Buckets.ladder_candidates(cap)):
        for mif in in_flight:
            candidates.append((tuple(ladder), int(mif)))
    best = None  # (elapsed_s, ladder, mif, stats)
    tried = rejected = 0
    for ladder, mif in candidates:
        tried += 1
        try:
            engine = ServingEngine(dpf, max_in_flight=mif, buckets=ladder,
                                   warmup=True)
            futs = [engine.submit(b) for b in stream]
            engine.drain()
            if not all(np.array_equal(r, f.result())
                       for r, f in zip(reference, futs)):
                rejected += 1
                if log:
                    log("  reject (diverged): %s mif=%d" % (ladder, mif))
                continue
            elapsed = float("inf")
            for _ in range(reps):
                engine = ServingEngine(dpf, max_in_flight=mif,
                                       buckets=ladder)
                t0 = time.perf_counter()
                futs = [engine.submit(b) for b in stream]
                engine.drain()
                elapsed = min(elapsed, time.perf_counter() - t0)
        except Exception as exc:
            rejected += 1
            if log:
                log("  reject (%s): %s mif=%d"
                    % (type(exc).__name__, ladder, mif))
            continue
        if log:
            log("  ladder=%s mif=%d -> %d qps"
                % (list(ladder), mif, int(total / elapsed)))
        if best is None or elapsed < best[0]:
            best = (elapsed, ladder, mif, engine.stats.as_dict())
    if best is None:
        raise AssertionError("no serving candidate passed the gate")
    elapsed, ladder, mif, stats = best
    record = {
        "knobs": {"buckets": list(ladder), "max_in_flight": mif},
        "measured": {
            "elapsed_s": round(elapsed, 6),
            "qps": int(total / elapsed),
            "trace": trace, "cap": cap, "reps": reps,
            "candidates_tried": tried, "rejected": rejected,
            "engine_stats": stats,
        },
        "fingerprint": device_fingerprint(),
        "gated": True,  # winner matched the blocking loop bit-for-bit
    }
    cache.store(key, record)
    return {**record, "searched": True}


def tune_serving_shape(*, n: int, cap: int, entry_size: int = 16,
                       prf_method: int = 0, cache=None, force=False,
                       reps: int = 2) -> dict:
    """Standalone-sweep entry: build a synthetic server for the shape,
    tune its serving knobs, and return a summary row."""
    import dpf_tpu

    dpf = dpf_tpu.DPF(prf=prf_method)
    table = np.random.default_rng(n ^ 0x5e12).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    dpf.eval_init(table)
    rec = tune_serving(dpf, cap=cap, cache=cache, force=force, reps=reps)
    m = rec["measured"]
    return {
        "entries": n, "cap": cap,
        "tuned_knobs": rec["knobs"],
        "qps": m["qps"], "elapsed_s": m["elapsed_s"],
        "candidates_tried": m["candidates_tried"],
        "rejected": m["rejected"],
        "from_cache": not rec["searched"],
    }


# --------------------------------------------------------- scheme router


def router_cache_key(*, n: int, entry_size: int, batch: int,
                     prf_method: int) -> str:
    """Tuning-cache key for the scheme router's knobs.  Like the
    scheme-winner key, the construction is the router's runtime ANSWER
    (it changes per batch), not part of the shape — scheme/radix pin to
    the ``any``/0 sentinels."""
    return cache_key("router", n=n, entry_size=entry_size, batch=batch,
                     prf_method=prf_method, scheme="any", radix=0)


def lookup_router_knobs(router, cap: int,
                        cache: TuningCache | None = None) -> dict | None:
    """Tuned router knobs (buckets, max_in_flight, ewma_alpha) for this
    table shape, or None.  ``router`` is anything exposing
    n / entry_size / prf_method (a ``serve.router.SchemeRouter`` mid-
    construction, or a prepared server).  Never raises — an unreadable
    cache is a miss."""
    try:
        cache = cache if cache is not None else default_cache()
        n = getattr(router, "n", None) or router.table_num_entries
        e = (getattr(router, "entry_size", None)
             or router.table_effective_entry_size)
        rec = cache.lookup(router_cache_key(
            n=int(n), entry_size=int(e), batch=cap,
            prf_method=router.prf_method))
        return rec.get("knobs") if rec else None
    except Exception:  # pragma: no cover — cache must never break serving
        return None


def cached_cost_table(*, n: int, entry_size: int, cap: int,
                      prf_method: int = 0,
                      cache: TuningCache | None = None) -> dict:
    """Cache-backed cost seeding for the digital twin: recover a
    ``{"construction@cap": seconds}`` table (the
    ``SchemeRouter.cost_table()`` spelling) from an EXACT cap-batch
    scheme-sweep entry's per-construction measured seconds — the same
    rows ``SchemeRouter._resolve_sticky`` seeds its EWMA from.  Lets a
    planner (``plan/capacity.py``) size a fleet for a fingerprint
    that has been tuned on this machine WITHOUT standing a router up.
    Never raises; empty dict on a cold cache."""
    from .search import scheme_cache_key
    out = {}
    try:
        cache = cache if cache is not None else default_cache()
        rec = cache.lookup(scheme_cache_key(
            n=int(n), entry_size=int(entry_size), batch=int(cap),
            prf_method=int(prf_method)))
        for row in (rec or {}).get("measured", {}).get(
                "per_construction", ()):
            lb, s = row.get("construction"), row.get("tuned_s")
            if lb and s:
                out["%s@%d" % (lb, int(cap))] = float(s)
    except Exception:   # cache must never break planning
        return {}
    return out


def tune_router(table, *, prf_method: int = 0, cap: int | None = None,
                trace=None, trace_kind: str | None = None,
                trace_kw: dict | None = None, in_flight=(1, 2),
                ladders=None, alphas=(0.25,), reps: int = 2,
                distinct: int = 8, cache: TuningCache | None = None,
                force: bool = False, log=None) -> dict:
    """Tune the scheme router's switch machinery against a chosen trace.

    Grid-searches (bucket ladder x ``max_in_flight`` x ``ewma_alpha``)
    for a ``serve.router.SchemeRouter`` over ``table``, replaying the
    trace's batch sizes back-to-back through each candidate (all three
    constructions prepared ONCE and shared across candidates).  Every
    candidate's every routed answer is equality-gated against the
    scalar oracle (``DPF.eval_cpu`` references, the load harness's key
    pools — ``bench_load._key_pool``); the elapsed-time winner
    persists under the ``router|...`` key, which
    ``SchemeRouter(buckets=None)`` consults at construction.  Like
    ``tune_serving``, an explicit trace always re-measures.
    """
    import dpf_tpu
    from ..serve import loadgen
    from ..serve.bench_load import _batch_for, _key_pool
    from ..serve.buckets import Buckets
    from ..serve.router import LABELS, SchemeRouter, build_servers

    cache = cache if cache is not None else default_cache()
    table = np.asarray(table, dtype=np.int32)
    n, entry_size = table.shape
    cap = int(cap or min(dpf_tpu.DPF.BATCH_SIZE, 512))
    key = router_cache_key(n=n, entry_size=entry_size, batch=cap,
                           prf_method=prf_method)
    if not force and trace is None and trace_kind is None:
        rec = cache.lookup(key)
        if rec is not None:
            return {**rec, "searched": False}

    trace = resolve_trace(cap, trace, trace_kind, trace_kw)
    if max(trace) > cap:
        raise ValueError("trace batch %d exceeds cap %d"
                         % (max(trace), cap))
    total = sum(trace)
    # one table upload per construction, shared by every candidate
    # (the router's own construction-spelling map, so the tuner can
    # never measure a differently-configured server than it tunes);
    # the key pools + scalar-oracle references are the load harness's
    # own machinery — one spelling across both harnesses
    servers = build_servers(table, LABELS, prf_method=prf_method)
    pools = {lb: _key_pool(srv, n, distinct,
                           b"router-tune-%s" % lb.encode())
             for lb, srv in servers.items()}

    def key_batch(lb, j, b):
        return _batch_for(pools[lb], j, b)

    candidates = []
    for ladder in (ladders if ladders is not None
                   else Buckets.ladder_candidates(cap)):
        for mif in in_flight:
            for alpha in alphas:
                candidates.append((tuple(ladder), int(mif),
                                   float(alpha)))
    best = None
    tried = rejected = 0
    for ladder, mif, alpha in candidates:
        tried += 1
        try:
            elapsed, stats = float("inf"), None
            for _ in range(reps):
                router = SchemeRouter(
                    None, servers=servers, buckets=ladder,
                    max_in_flight=mif, ewma_alpha=alpha, cap=cap)
                t0 = time.perf_counter()
                outs = []
                for j, b in enumerate(trace):
                    dec = router.route(b)
                    keys, idxs = key_batch(dec.construction, j, b)
                    outs.append((dec, idxs, router.submit(dec, keys)))
                for _, _, fut in outs:
                    fut.result()
                rep_s = time.perf_counter() - t0
                if rep_s < elapsed:   # keep the stats OF the kept rep
                    elapsed, stats = rep_s, router.stats()
                # gate EVERY rep: the probe-seeded cost model varies
                # run to run, so different reps can route batches to
                # different (construction, bucket) programs — a winner
                # marked "gated" must have had every program it ran
                # checked (results are already materialized; the gate
                # is an index + compare per batch)
                for dec, idxs, fut in outs:
                    ref = pools[dec.construction][1][idxs]
                    if not np.array_equal(fut.result(), ref):
                        raise AssertionError("routed answers diverged")
        except Exception as exc:
            rejected += 1
            if log:
                log("  reject (%s): %s mif=%d a=%.2f"
                    % (type(exc).__name__, ladder, mif, alpha))
            continue
        if log:
            log("  ladder=%s mif=%d a=%.2f -> %d qps"
                % (list(ladder), mif, alpha, int(total / elapsed)))
        if best is None or elapsed < best[0]:
            best = (elapsed, ladder, mif, alpha, stats)
    if best is None:
        raise AssertionError("no router candidate passed the gate")
    elapsed, ladder, mif, alpha, stats = best
    record = {
        "knobs": {"buckets": list(ladder), "max_in_flight": mif,
                  "ewma_alpha": alpha},
        "measured": {
            "elapsed_s": round(elapsed, 6),
            "qps": int(total / elapsed),
            "trace": trace, "cap": cap, "reps": reps,
            "candidates_tried": tried, "rejected": rejected,
            "router_stats": stats,
            # dispatch pressure per compiled shape under the winning
            # ladder (the trace here is a bare size list, so these are
            # counts, not Hz — timestamped traces get real rates from
            # loadgen.bucket_rates directly)
            "trace_bucket_dispatches": {
                "%d" % bk: int(c)
                for bk, c in loadgen.bucket_rates(
                    trace, ladder, duration_s=1.0).items()},
        },
        "fingerprint": device_fingerprint(),
        "gated": True,  # every routed answer matched the eval_cpu oracle
    }
    cache.store(key, record)
    return {**record, "searched": True}


# -------------------------------------------------------- cluster scatter


def cluster_cache_key(*, n: int, entry_size: int, batch: int,
                      prf_method: int, hosts: int) -> str:
    """Tuning-cache key for the multi-host scatter knobs.  The host
    count rides in the mesh tag slot ("h<H>"): a 2-host and an 8-host
    cluster scatter the same table very differently (per-host granule
    size changes the per-dispatch work), so their knobs must not be
    confused — same grammar move as the mesh-tagged serve keys."""
    return cache_key("cluster", n=n, entry_size=entry_size, batch=batch,
                     prf_method=prf_method, scheme="logn", radix=2,
                     mesh="h%d" % int(hosts))


def lookup_cluster_knobs(*, n: int, entry_size: int, hosts: int,
                         prf_method: int, cap: int,
                         cache: TuningCache | None = None) -> dict | None:
    """Tuned (buckets, max_in_flight) for this cluster shape, or None.
    ``ClusterRouter.local`` consults this when knobs are not pinned.
    Never raises — an unreadable cache is a miss."""
    try:
        cache = cache if cache is not None else default_cache()
        rec = cache.lookup(cluster_cache_key(
            n=int(n), entry_size=int(entry_size), batch=int(cap),
            prf_method=int(prf_method), hosts=int(hosts)))
        return rec.get("knobs") if rec else None
    except Exception:  # pragma: no cover — cache must never break serving
        return None


def tune_cluster(table, *, hosts: int = 2, prf_method: int = 0,
                 cap: int | None = None, trace=None,
                 trace_kind: str | None = None,
                 trace_kw: dict | None = None, in_flight=(1, 2),
                 ladders=None, reps: int = 2, distinct: int = 8,
                 cache: TuningCache | None = None, force: bool = False,
                 log=None) -> dict:
    """Tune the cluster front-end's scatter knobs against a trace.

    Grid-searches (bucket ladder x ``max_in_flight``) for a simulated
    ``parallel.cluster.ClusterRouter`` over ``table`` — the in-process
    tier runs the identical scatter/merge code the multiprocess tier
    does, so its knob ranking transfers.  Every candidate's every
    merged answer is equality-gated against the scalar oracle
    (``DPF.eval_cpu``); the winner persists under the ``cluster|...``
    key.  Like the other tuners, an explicit trace re-measures.
    """
    import dpf_tpu
    from ..parallel.cluster import ClusterRouter
    from ..serve.buckets import Buckets

    cache = cache if cache is not None else default_cache()
    table = np.asarray(table, dtype=np.int32)
    n, entry_size = table.shape
    cap = int(cap or min(dpf_tpu.DPF.BATCH_SIZE, 512))
    key = cluster_cache_key(n=n, entry_size=entry_size, batch=cap,
                            prf_method=prf_method, hosts=hosts)
    if not force and trace is None and trace_kind is None:
        rec = cache.lookup(key)
        if rec is not None:
            return {**rec, "searched": False}

    trace = resolve_trace(cap, trace, trace_kind, trace_kw)
    if max(trace) > cap:
        raise ValueError("trace batch %d exceeds cap %d"
                         % (max(trace), cap))
    total = sum(trace)
    oracle = dpf_tpu.DPF(prf=prf_method)
    oracle.eval_init(table)
    ks = [oracle.gen((i * 0x9E3779B1) % n, n,
                     seed=b"cluster-tune-%d" % i)[0]
          for i in range(distinct)]
    refs = oracle.eval_cpu(ks)
    stream = [([ks[(j + i) % distinct] for i in range(b)],
               [(j + i) % distinct for i in range(b)])
              for j, b in enumerate(trace)]

    candidates = []
    for ladder in (ladders if ladders is not None
                   else Buckets.ladder_candidates(cap)):
        for mif in in_flight:
            candidates.append((tuple(ladder), int(mif)))
    best = None
    tried = rejected = 0
    for ladder, mif in candidates:
        tried += 1
        try:
            elapsed, stats = float("inf"), None
            for _ in range(reps):
                c = ClusterRouter.local(
                    table, hosts=hosts, oracle=oracle, buckets=ladder,
                    engine_kw={"max_in_flight": mif})
                c.warmup()
                t0 = time.perf_counter()
                outs = [(idxs, c.submit(keys)) for keys, idxs in stream]
                for _, fut in outs:
                    fut.result()
                rep_s = time.perf_counter() - t0
                if rep_s < elapsed:
                    elapsed, stats = rep_s, c.stats()
                for idxs, fut in outs:    # gate every rep's answers
                    if not np.array_equal(fut.result(), refs[idxs]):
                        raise AssertionError("merged shares diverged")
        except Exception as exc:
            rejected += 1
            if log:
                log("  reject (%s): %s mif=%d"
                    % (type(exc).__name__, ladder, mif))
            continue
        if log:
            log("  ladder=%s mif=%d -> %d qps"
                % (list(ladder), mif, int(total / elapsed)))
        if best is None or elapsed < best[0]:
            best = (elapsed, ladder, mif, stats)
    if best is None:
        raise AssertionError("no cluster candidate passed the gate")
    elapsed, ladder, mif, stats = best
    record = {
        "knobs": {"buckets": list(ladder), "max_in_flight": mif},
        "measured": {
            "elapsed_s": round(elapsed, 6),
            "qps": int(total / elapsed),
            "trace": trace, "cap": cap, "hosts": hosts, "reps": reps,
            "candidates_tried": tried, "rejected": rejected,
            "cluster_stats": stats,
        },
        "fingerprint": device_fingerprint(),
        "gated": True,  # every merged share matched the eval_cpu oracle
    }
    cache.store(key, record)
    return {**record, "searched": True}
