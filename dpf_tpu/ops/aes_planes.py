"""Plane-domain bitsliced AES-128 for Pallas TPU level kernels.

``core/aes_bitsliced.py`` packs AES instances into word bits along the
*flattened element* axis, which needs minor-dim reshapes and byte-axis
gathers — fine under XLA, hostile inside a Mosaic kernel.  This module is
the Pallas-native re-expression of the same cipher (the hand-scheduled
path the reference gives its headline PRF via the templated hybrid
kernel, ``dpf_gpu/dpf/dpf_hybrid.cu:258-272`` + ``dpf_gpu/prf/prf.cu``):

* Instance layout: a GGM level step's elements are ``[32 keys, W
  columns]``; the 32 key rows are bit-packed into uint32 words (one
  ``_transpose32`` shift-swap cascade per limb) so every plane tensor is
  ``[1, W]`` with the column axis riding the 128-wide lanes.
* Every AES byte-axis manipulation (ShiftRows, RotWord, MixColumns'
  row rotation) is a static slice + concatenate — no gathers, no
  minor-dim reshapes, so the whole cipher lowers through Mosaic.
* The GGM codeword select + 128-bit add also run in plane domain: the
  select is three boolean ops per bit against per-key codeword bit words
  (SMEM scalars), the add is a ripple-carry full-adder chain — ~20
  word-equivalent ops per child, amortized 32x by the packing.
* S-box circuits are shared with the XLA path (``aes_sbox_bp`` /
  ``aes_sbox_circuit`` / chain) — they are pure plane-op circuits.

Semantics are bit-identical to ``prf_ref.prf_aes128`` /
``aes_bitsliced.aes128_multi_bitsliced`` (asserted in tests).

AES is compute-bound (~1.4K plane ops per 16-byte block vs 16 B of HBM
traffic), so unlike ChaCha there is no benefit in keeping whole subtrees
VMEM-resident; the kernel here is ONE level step (PRF children + select
+ add fused), dispatched per level by the drivers in ``core/expand.py``
and ``core/radix4.py`` — each kernel compiles in seconds, which also
keeps the TPU-relay compile-time discipline (docs/STATUS.md).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core.aes_bitsliced import (_RCON_VALS, _SHIFT_ROWS_BYTE,
                                  _sbox_bits, _transpose32)

TILE_KEYS = 32       # key rows bit-packed per word (fixed by uint32)
DEFAULT_TW = 256     # column tile: 32*TW instances, ~1 MB VMEM live state


def pack32(rows):
    """32 word tensors (key rows, any common shape) -> 32 bit planes.

    Same convention as ``aes_bitsliced.pack_planes`` over a 32-element
    block: plane b holds bit b of every key, key order within a word
    permuted by a fixed involution (harmless — ``unpack32`` inverts it,
    and host-side codeword packing uses the same convention).
    """
    return _transpose32(list(rows))[::-1]


def unpack32(planes):
    """Inverse of ``pack32``: 32 bit planes -> 32 key-row words."""
    return _transpose32(list(planes)[::-1])


# ---------------------------------------------------------------------------
# Plane-domain AES core (state = 8 tensors [16, W]; byte ops = static
# slices + concats; instances = 32 packed keys x W columns)
# ---------------------------------------------------------------------------

def _byte_select(x, perm):
    return jnp.concatenate([x[i:i + 1] for i in perm], axis=0)


def _shift_rows(bits, m: int = 1):
    """Byte permutation; ``m`` fused states tile the 16-byte pattern."""
    if m == 1:
        perm = _SHIFT_ROWS_BYTE
    else:
        perm = np.concatenate([_SHIFT_ROWS_BYTE + 16 * k
                               for k in range(m)])
    return [_byte_select(b, perm) for b in bits]


def _xtime_bits(bits):
    out = [bits[7]]
    for i in range(1, 8):
        v = bits[i - 1]
        if (0x1B >> i) & 1:
            v = v ^ bits[7]
        out.append(v)
    return out


def _mix_columns(bits):
    """Works on any multiple of 16 bytes (M fused states = 4M columns);
    major-axis reshapes only (Mosaic-safe)."""
    a4 = [b.reshape(-1, 4, b.shape[-1]) for b in bits]  # [col, row, W]
    nxt = [jnp.concatenate([a[:, 1:], a[:, :1]], axis=1) for a in a4]
    x = [a4[i] ^ nxt[i] for i in range(8)]
    xt = _xtime_bits(x)
    out = []
    for i in range(8):
        t = (a4[i][:, 0:1] ^ a4[i][:, 1:2] ^ a4[i][:, 2:3]
             ^ a4[i][:, 3:4])
        out.append((a4[i] ^ t ^ xt[i]).reshape(bits[i].shape))
    return out


def _ark_tiled(st, rk, m_cnt):
    """AddRoundKey on a fused state via leading-axis rk tiling (concat,
    not broadcast-reshape: leading-axis concat is the Mosaic-safest)."""
    if m_cnt == 1:
        return [st[i] ^ rk[i] for i in range(8)]
    return [st[i] ^ jnp.concatenate([rk[i]] * m_cnt, axis=0)
            for i in range(8)]


def _round_fused(st, rk, m_cnt, rcon, ones_row, sbox):
    """One fused round on M fused states (planes [16*M, W]) + schedule
    step.  ``rcon`` is either a static int (unrolled rounds: the byte-0
    flip folds to a constant) or a traced uint32 scalar (fori_loop
    rounds: flip via a computed mask).  ShiftRows/MixColumns/ARK
    downstream also run once on the fused tensor — the per-round op
    count no longer scales with M.
    """
    rot = [jnp.concatenate([rk[i][13:14], rk[i][14:15], rk[i][15:16],
                            rk[i][12:13]], axis=0) for i in range(8)]
    fused_in = [jnp.concatenate([st[i], rot[i]], axis=0)
                for i in range(8)]
    fused_out = _sbox_bits(fused_in, ones_row, sbox)
    sub = [f[:16 * m_cnt] for f in fused_out]
    t = [f[16 * m_cnt:16 * m_cnt + 4] for f in fused_out]
    if isinstance(rcon, (int, np.integer)):
        t = [jnp.concatenate(
            [t[i][0:1] ^ np.uint32(0xFFFFFFFF), t[i][1:]], axis=0)
            if (int(rcon) >> i) & 1 else t[i] for i in range(8)]
    else:
        masks = [(np.uint32(0) - ((rcon >> np.uint32(i))
                                  & np.uint32(1))).astype(jnp.uint32)
                 for i in range(8)]
        t = [jnp.concatenate([t[i][0:1] ^ masks[i], t[i][1:]], axis=0)
             for i in range(8)]
    new_rk = []
    for i in range(8):
        w0 = rk[i][0:4] ^ t[i]
        w1 = w0 ^ rk[i][4:8]
        w2 = w1 ^ rk[i][8:12]
        w3 = w2 ^ rk[i][12:16]
        new_rk.append(jnp.concatenate([w0, w1, w2, w3], axis=0))
    return sub, new_rk


def aes128_multi_planes(key_planes, n_pts: int, sbox: str | None = None,
                        unroll: bool = True):
    """AES of positions 0..n_pts-1 under per-instance keys, plane domain.

    key_planes: 128 tensors [1, W] — bit t (= limb t//32, bit t%32) of
    every instance's seed.  Returns ``n_pts`` lists of 128 output planes
    with the same bit indexing, matching ``prf_ref.prf_aes128(seed, b)``.

    ``unroll=True`` (the Pallas kernel) unrolls the 9 uniform middle
    rounds; ``unroll=False`` (the non-Pallas reference path) runs them in
    a ``fori_loop`` so the traced graph stays one round body deep — the
    fully-unrolled cipher times out XLA-CPU compilation when several
    levels stack in one program.
    """
    rk = [jnp.concatenate([key_planes[8 * byte + i] for byte in range(16)],
                          axis=0) for i in range(8)]  # 8 x [16, W]
    ones_row = jnp.full_like(key_planes[0], np.uint32(0xFFFFFFFF))

    # plaintext b: only byte 0 nonzero; fold into the initial ARK.
    # States live FUSED back to back on the byte axis ([16*M, W] planes)
    # for the whole cipher.
    st = []
    for i in range(8):
        blocks = []
        for b in range(n_pts):
            if (b >> i) & 1:
                blocks.append(jnp.concatenate(
                    [rk[i][0:1] ^ np.uint32(0xFFFFFFFF), rk[i][1:]],
                    axis=0))
            else:
                blocks.append(rk[i])
        st.append(blocks[0] if n_pts == 1
                  else jnp.concatenate(blocks, axis=0))

    def middle(st, rk, rcon):
        sub, rk = _round_fused(st, rk, n_pts, rcon, ones_row, sbox)
        return _ark_tiled(_mix_columns(_shift_rows(sub, n_pts)), rk,
                          n_pts), rk

    if unroll:
        for rnd in range(1, 10):
            st, rk = middle(st, rk, _RCON_VALS[rnd])
    else:
        # rcon is carried as a scalar and stepped by xtime in GF(256)
        # (rcon_{r+1} = xtime(rcon_r)) instead of indexing a u32[10]
        # constant: a captured constant array is rejected inside Pallas
        # kernel bodies, and the recurrence is two scalar ops.
        def body(r, carry):
            s, c, rcon = carry
            sl, rkl = middle([s[i] for i in range(8)],
                             [c[i] for i in range(8)], rcon)
            rcon = ((rcon << np.uint32(1))
                    ^ ((rcon >> np.uint32(7)) * np.uint32(0x11B))
                    ) & np.uint32(0xFF)
            return (jnp.stack(sl), jnp.stack(rkl), rcon)

        carry = (jnp.stack(st), jnp.stack(rk), jnp.uint32(1))
        carry = jax.lax.fori_loop(0, 9, body, carry)
        st = [carry[0][i] for i in range(8)]
        rk = [carry[1][i] for i in range(8)]

    sub, rk = _round_fused(st, rk, n_pts, _RCON_VALS[10], ones_row, sbox)
    fin = _ark_tiled(_shift_rows(sub, n_pts), rk, n_pts)
    outs = []
    for b in range(n_pts):
        outs.append([fin[p % 8][16 * b + p // 8:16 * b + p // 8 + 1]
                     for p in range(128)])
    return outs


# ---------------------------------------------------------------------------
# GGM plumbing in plane domain
# ---------------------------------------------------------------------------

def _add128_planes(a, b):
    """128-bit add mod 2^128 as a ripple-carry full-adder chain."""
    out = []
    carry = None
    for t in range(128):
        axb = a[t] ^ b[t]
        if carry is None:
            out.append(axb)
            carry = a[t] & b[t]
        else:
            out.append(axb ^ carry)
            carry = (a[t] & b[t]) | (carry & axb)
    return out


def pack_cw_planes(cw_lvl):
    """Host-side codeword bit packing for the level kernel.

    cw_lvl: [B, A, 4] uint32 (B % 32 == 0) — this level's codewords.
    Returns [B//32, A*128] uint32: word (tile, a*128 + t) holds bit t of
    the A-th codeword of the tile's 32 keys, packed with the ``pack32``
    key order (so it composes with the in-kernel seed packing).
    """
    bsz, a_cnt, _ = cw_lvl.shape
    assert bsz % TILE_KEYS == 0
    v = cw_lvl.reshape(bsz // TILE_KEYS, TILE_KEYS, a_cnt * 4)
    rows = [v[:, k, :] for k in range(TILE_KEYS)]     # [tiles, A*4] each
    planes = _transpose32(rows)[::-1]                 # 32 x [tiles, A*4]
    # bit index t = 32*limb + plane  ->  stack planes minor, limbs next
    stacked = jnp.stack(planes, axis=-1)              # [tiles, A*4, 32]
    return stacked.reshape(bsz // TILE_KEYS, a_cnt, 4 * 32).reshape(
        bsz // TILE_KEYS, a_cnt * 128)


def _level_planes_core(seed_limbs, cw1_at, cw2_at, arity: int,
                       sbox: str | None, unroll: bool = True):
    """Shared level-step body (kernel and non-Pallas reference).

    seed_limbs: 4 tensors [32, W] (key rows x columns, limb l).
    cw*_at(i): scalar accessor for codeword bit word i (i = b*128 + t).
    Returns ``arity`` lists of 4 limb tensors [32, W] (child b).
    """
    planes = []
    for l in range(4):
        rows = [seed_limbs[l][k:k + 1, :] for k in range(TILE_KEYS)]
        planes.extend(pack32(rows))                   # 128 x [1, W]
    sel = planes[0]                                   # LSB plane
    outs = aes128_multi_planes(planes, arity, sbox, unroll)
    res = []
    for b in range(arity):
        cw = []
        for t in range(128):
            c1 = cw1_at(b * 128 + t)
            c2 = cw2_at(b * 128 + t)
            cw.append(c1 ^ (sel & (c1 ^ c2)))
        child = _add128_planes(outs[b], cw)
        res.append([jnp.concatenate(unpack32(child[32 * l:32 * l + 32]),
                                    axis=0) for l in range(4)])
    return res


def _make_aes_level_kernel(arity: int, sbox: str | None,
                           unroll: bool = True):
    def kernel(cw1p_ref, cw2p_ref, seeds_ref, *out_refs):
        # seeds_ref [4, 32, TW]; cw*p_ref [1, arity*128] (SMEM);
        # out_refs: arity x [4, 32, TW]
        res = _level_planes_core(
            [seeds_ref[l] for l in range(4)],
            lambda i: cw1p_ref[0, i], lambda i: cw2p_ref[0, i],
            arity, sbox, unroll=unroll)
        for b in range(arity):
            for l in range(4):
                out_refs[b][l] = res[b][l]

    return kernel


def aes_level_step_ref(seeds, cw1_lvl, cw2_lvl, *, arity: int = 2,
                       sbox: str | None = None):
    """Non-Pallas reference of ``aes_level_step_pallas``: identical math
    (same packing, same plane circuits, same accessors) as plain traced
    jnp.  Exists so the full driver glue (cw slicing, grouping, scan,
    contraction) is testable without interpret-mode Pallas cost; the
    kernel itself is asserted against this in the small interpret tests.
    """
    bsz, w, _ = seeds.shape
    pb = (-bsz) % TILE_KEYS
    if pb:
        seeds = jnp.pad(seeds, ((0, pb), (0, 0), (0, 0)))
        cw1_lvl = jnp.pad(cw1_lvl, ((0, pb), (0, 0), (0, 0)))
        cw2_lvl = jnp.pad(cw2_lvl, ((0, pb), (0, 0), (0, 0)))
    bp = bsz + pb
    cw1p = pack_cw_planes(cw1_lvl)
    cw2p = pack_cw_planes(cw2_lvl)
    tiles = []
    for ti in range(bp // TILE_KEYS):
        sl = slice(ti * TILE_KEYS, (ti + 1) * TILE_KEYS)
        res = _level_planes_core(
            [seeds[sl, :, l] for l in range(4)],
            lambda i, ti=ti: cw1p[ti, i], lambda i, ti=ti: cw2p[ti, i],
            arity, sbox, unroll=False)
        # res[b][l]: [32, w] -> node-major children [32, A*w, 4]
        kids = jnp.stack([jnp.stack(res[b], axis=-1)
                          for b in range(arity)], axis=2)
        tiles.append(kids.reshape(TILE_KEYS, arity * w, 4))
    return jnp.concatenate(tiles, axis=0)[:bsz]


def _aes_level_step_impl(seeds, cw1_lvl, cw2_lvl, *, arity: int = 2,
                         sbox: str | None = None, interpret: bool = False,
                         tw: int = DEFAULT_TW, unroll: bool = True):
    """One AES GGM level via the plane-domain Pallas kernel.

    seeds: [B, w, 4] u32; cw*_lvl: [B, arity, 4] u32 (this level's
    codewords, branch-major).  Returns [B, arity*w, 4] children in
    node-major order (child b of node j at arity*j + b) — the same
    convention as ``expand._level_step_pair`` / ``radix4._level_step_mixed``,
    so the standard permuted tables apply unchanged.

    ``unroll=False`` runs the 9 middle rounds in a ``fori_loop`` — a
    ~10x smaller traced graph, used by the interpret-mode tests (the
    unrolled cipher leg is pinned directly by the cipher-vs-reference
    tests); the production TPU path keeps the unrolled body.
    """
    from jax.experimental import pallas as pl

    bsz, w, _ = seeds.shape
    tw = min(tw, w)
    pb = (-bsz) % TILE_KEYS
    pw = (-w) % tw
    if pb or pw:
        seeds = jnp.pad(seeds, ((0, pb), (0, pw), (0, 0)))
        cw1_lvl = jnp.pad(cw1_lvl, ((0, pb), (0, 0), (0, 0)))
        cw2_lvl = jnp.pad(cw2_lvl, ((0, pb), (0, 0), (0, 0)))
    bp, wp = bsz + pb, w + pw

    sm = jnp.transpose(seeds, (2, 0, 1))              # [4, B, w]
    cw1p = pack_cw_planes(cw1_lvl)                    # [tiles, A*128]
    cw2p = pack_cw_planes(cw2_lvl)

    try:
        from jax.experimental.pallas import tpu as pltpu
        smem = pltpu.SMEM
    except ImportError:                               # interpret-only envs
        smem = None
    cw_spec = pl.BlockSpec(
        (1, arity * 128), lambda i, j: (i, 0),
        **({"memory_space": smem} if smem is not None else {}))

    from .pallas_level import _compiler_params

    grid = (bp // TILE_KEYS, wp // tw)
    kernel = _make_aes_level_kernel(arity, sbox, unroll)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        # key tiles and column tiles are fully independent
        compiler_params=_compiler_params(("parallel", "parallel")),
        in_specs=[
            cw_spec,
            cw_spec,
            pl.BlockSpec((4, TILE_KEYS, tw), lambda i, j: (0, i, j)),
        ],
        out_specs=[pl.BlockSpec((4, TILE_KEYS, tw), lambda i, j: (0, i, j))
                   ] * arity,
        out_shape=[jax.ShapeDtypeStruct((4, bp, wp), jnp.uint32)] * arity,
        interpret=interpret,
    )(cw1p, cw2p, sm)

    children = jnp.stack([jnp.transpose(o, (1, 2, 0)) for o in outs],
                         axis=2)                      # [B, w, A, 4]
    return children.reshape(bp, arity * wp, 4)[:bsz, :arity * w]


_aes_level_step_jit = functools.partial(
    jax.jit, static_argnames=("arity", "sbox", "interpret", "tw",
                              "unroll"))(_aes_level_step_impl)


def aes_level_step_pallas(seeds, cw1_lvl, cw2_lvl, *, arity: int = 2,
                          sbox: str | None = None, interpret: bool = False,
                          tw: int = DEFAULT_TW, unroll: bool = True):
    """Jit-wrapped plane-AES level kernel; ``interpret=True`` runs
    EAGERLY — interpret-mode pallas_call under jit makes XLA-CPU compile
    blow up super-linearly with grid size (see
    ``pallas_level.chacha_level_step_pallas``)."""
    fn = _aes_level_step_impl if interpret else _aes_level_step_jit
    return fn(seeds, cw1_lvl, cw2_lvl, arity=arity, sbox=sbox,
              interpret=interpret, tw=tw, unroll=unroll)
