"""Fused sqrt-N PRF-grid -> contract Pallas TPU kernel.

The XLA sqrt-N path (``core/sqrtn._eval_contract_batched_jit``) scans
``[B, rc, K]`` PRF grid slabs through HBM: every scan step materializes
the slab, applies the LSB codeword select/add, and hands ``matmul128``
a ``[B, rc*K]`` leaf-share tensor — at ChaCha's ~25 int-ops/byte that
slab traffic is comparable to the compute.  This module supplies the
fused alternative (the sqrt-N half of the ROADMAP megakernel item,
completing ``pallas_level.subtree_contract_pallas``'s logn half):

grid ``(B/TB, R/rc)`` — for each key tile, one ``rc``-row tile of the
``[R, K]`` PRF grid is expanded **entirely in VMEM** (one cipher call
over the ``[TB, rc*K]`` cell planes; the block-PRG ids evaluate one
512-bit core block per FOUR grid rows and interleave, exactly
``sqrtn._grid_vals``), the low-limb codeword select/add lands in
registers, and the ``[TB, E]`` table contraction accumulates in the
VMEM-resident output block (the documented reduction-dim pattern: the
innermost grid dimension does not appear in the output index map).  The
one-hot leaf share never touches HBM.

Cell order is natural: cell ``m = t*K + c`` of a tile holds grid row
``row0 + t``, column seed ``c`` — table rows line up with no
permutation, and a traced ``row0`` (the sharded path's per-shard row
base) rides in as a tiny ``[steps, 1]`` VMEM operand.

Only the low 32 output bits are contracted, and 128-bit adds carry
upward only, so the codeword add needs just the low limb — the kernel
ships ``cw*[..., 0]`` planes and skips the carry chain entirely.

Correctness: asserted against the scan-path oracle in tests (interpret
mode on CPU, compiled on TPU).  ChaCha20-12/Salsa20-12 cores and their
block-PRG variants; AES stays on the XLA path (see
``pallas_level``'s module docstring).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .pallas_level import _BLK_CORES, _CORES, _compiler_params

# default tile knobs: widest live state = 16 cipher words x [TB, cells]
# u32 (the block-PRG ids quarter that — one block per 4 rows)
PALLAS_SQRT_TB = 32         # key tile (sublane-friendly multiple of 8)
PALLAS_SQRT_MAX_CELLS = 2048  # rc*K per tile -> ~4 MB cipher state


def pallas_sqrt_unsupported(prf_method: int, r: int) -> str | None:
    """Why the grid kernel cannot run this shape (None = it can).

    Callers that resolved ``kernel_impl="pallas"`` degrade to the xla
    scan path with provenance (``note_swallowed``) instead of raising —
    only an EXPLICIT pallas pin surfaces the reason as an error."""
    if prf_method not in _CORES and prf_method not in _BLK_CORES:
        return ("prf id %d has no Pallas plane core (AES stays on the "
                "XLA dispatch path)" % prf_method)
    if prf_method in _BLK_CORES and r % 4:
        return ("block-PRG sqrt-N grid kernel needs R (%d) to be a "
                "multiple of 4 (the 4-rows-per-core-block interleave "
                "cannot straddle a tile edge)" % r)
    return None


def pallas_sqrt_row_chunk(r: int, k: int,
                          row_chunk: int | None = None) -> int:
    """Grid rows per kernel step.  The kernel's live state is the
    ``[TB, rc*K]`` cipher planes in VMEM, so the bound is the CELL count
    (``PALLAS_SQRT_MAX_CELLS``), not the XLA scan's 64 MiB HBM slab.
    Explicit/tuned values obey the shared row-chunk rules (divide R,
    multiple of 4 when chunking — ``sqrtn._resolve_row_chunk``) and are
    then silently halved down to the cell cap: the accumulation order
    changes, the bits do not (int32 adds wrap)."""
    from ..core.sqrtn import ROW_CHUNK_FLOOR, _resolve_row_chunk
    rc = r if row_chunk is None else _resolve_row_chunk(r, k, 1, row_chunk)
    # halving preserves "divides R"; the %8 guard keeps rc a multiple
    # of 4 all the way down to the 4-row interleave floor
    while rc * k > PALLAS_SQRT_MAX_CELLS and rc > ROW_CHUNK_FLOOR \
            and rc % 8 == 0:
        rc //= 2
    return rc


def _make_sqrt_kernel(prf_method: int, tb: int, rc: int, k: int):
    """Kernel body for one (key tile, row tile) grid step."""
    from jax.experimental import pallas as pl

    blk = _BLK_CORES.get(prf_method)
    core = None if blk is not None else _CORES[prf_method]
    cells = rc * k

    def kernel(row0_ref, seeds_ref, cw1_ref, cw2_ref, table_ref, out_ref):
        j = pl.program_id(1)
        row0 = row0_ref[0, 0]                          # this tile's base row
        s = [seeds_ref[i] for i in range(4)]           # [TB, K]
        # cell m = t*K + c: grid row row0+t under column seed c —
        # natural order, matching the table tile rows directly
        if blk is not None:
            # ONE core block per 4 grid rows: counter plane c for rows
            # 4c..4c+3 (row0 is a multiple of 4 by the row-chunk rules)
            nctr = rc // 4
            planes = [jnp.broadcast_to(p[:, None, :], (tb, nctr, k))
                      .reshape(tb, nctr * k) for p in s]
            ctr = ((row0 >> np.uint32(2))
                   + lax.broadcasted_iota(jnp.uint32, (tb, nctr, k), 1)
                   .reshape(tb, nctr * k))
            out16 = blk(planes, ctr)
            # row 4c+g = block words [4g..4g+3] MSW-first, so the low
            # limb is word 4g+3 (``_grid_vals``/``_blk_group``)
            val0 = jnp.stack([out16[4 * g + 3].reshape(tb, nctr, k)
                              for g in range(4)],
                             axis=2).reshape(tb, cells)
        else:
            planes = [jnp.broadcast_to(p[:, None, :], (tb, rc, k))
                      .reshape(tb, cells) for p in s]
            pos = (row0 + lax.broadcasted_iota(jnp.uint32, (tb, rc, k), 1)
                   .reshape(tb, cells))
            val0 = core(planes, pos)[0]
        sel = (s[0] & np.uint32(1)).astype(jnp.bool_)  # [TB, K]
        cw_lo = jnp.where(
            jnp.broadcast_to(sel[:, None, :], (tb, rc, k))
            .reshape(tb, cells),
            jnp.broadcast_to(cw2_ref[:][:, :, None], (tb, rc, k))
            .reshape(tb, cells),
            jnp.broadcast_to(cw1_ref[:][:, :, None], (tb, rc, k))
            .reshape(tb, cells))
        leaves = (val0 + cw_lo).astype(jnp.int32)      # [TB, cells]
        contrib = lax.dot_general(
            leaves, table_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)          # x [E, cells]

        @pl.when(j == 0)
        def _():
            out_ref[:] = contrib

        @pl.when(j > 0)
        def _():
            out_ref[:] = out_ref[:] + contrib

    return kernel


def _sqrt_grid_contract_impl(seeds, cw1, cw2, table, row0, *,
                             prf_method: int, row_chunk: int | None = None,
                             interpret=False, tb: int | None = None):
    """Traceable launcher (the sharded per-shard body calls this inside
    its own jit/shard_map with a TRACED ``row0``).

    seeds: [B, K, 4] u32; cw1/cw2: [B, R, 4] u32; table: [R*K, E] int32
    natural-order rows for grid rows row0..row0+R-1.  Returns [B, E]
    int32 shares, bit-identical to the scan oracle.
    """
    from jax.experimental import pallas as pl

    bsz, k, _ = seeds.shape
    r = cw1.shape[1]
    e = table.shape[1]
    assert table.shape[0] == r * k, (table.shape, r, k)
    reason = pallas_sqrt_unsupported(prf_method, r)
    if reason:
        raise ValueError(reason)
    rc = pallas_sqrt_row_chunk(r, k, row_chunk)
    steps = r // rc

    tb = tb or min(PALLAS_SQRT_TB, max(8, bsz))
    pb = (-bsz) % tb
    if pb:
        seeds = jnp.pad(seeds, ((0, pb), (0, 0), (0, 0)))
        cw1 = jnp.pad(cw1, ((0, pb), (0, 0), (0, 0)))
        cw2 = jnp.pad(cw2, ((0, pb), (0, 0), (0, 0)))
    bp = bsz + pb

    sm = jnp.transpose(seeds, (2, 0, 1))               # [4, B, K]
    cw1_lo = cw1[:, :, 0]                              # [B, R] low limbs
    cw2_lo = cw2[:, :, 0]
    table_t = table.T                                  # [E, R*K]
    row0s = (jnp.asarray(row0, jnp.uint32)
             + jnp.arange(steps, dtype=jnp.uint32)
             * jnp.uint32(rc))[:, None]                # [steps, 1]

    grid = (bp // tb, steps)
    kernel = _make_sqrt_kernel(prf_method, tb, rc, k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((4, tb, k), lambda i, j: (0, i, 0)),
            pl.BlockSpec((tb, rc), lambda i, j: (i, j)),
            pl.BlockSpec((tb, rc), lambda i, j: (i, j)),
            pl.BlockSpec((e, rc * k), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((tb, e), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, e), jnp.int32),
        interpret=interpret,
        # key tiles are independent; the row-tile axis accumulates into
        # the same [tb, E] output block (reduction dim -> "arbitrary")
        compiler_params=_compiler_params(("parallel", "arbitrary")),
    )(row0s, sm, cw1_lo, cw2_lo, table_t)
    return out[:bsz]


_sqrt_grid_contract_jit = functools.partial(
    jax.jit, static_argnames=("prf_method", "row_chunk", "interpret",
                              "tb"))(_sqrt_grid_contract_impl)


def sqrt_grid_contract_pallas(seeds, cw1, cw2, table, *, prf_method: int,
                              row_chunk: int | None = None, row0=0,
                              interpret=False, tb: int | None = None):
    """Jit-wrapped fused sqrt-N grid kernel; ``interpret=True`` runs
    EAGERLY (see ``pallas_level.chacha_level_step_pallas`` —
    interpret-under-jit compile blows up super-linearly on XLA-CPU).

    ``row0`` may be a traced uint32 scalar (the sharded path's
    per-shard row base); already-traced callers get the impl inlined.
    """
    args = (jnp.asarray(seeds), jnp.asarray(cw1), jnp.asarray(cw2),
            jnp.asarray(table), row0)
    fn = (_sqrt_grid_contract_impl if interpret
          else _sqrt_grid_contract_jit)
    return fn(*args, prf_method=prf_method, row_chunk=row_chunk,
              interpret=interpret, tb=tb)
