"""Fused sqrt-N PRF-grid -> contract Pallas TPU kernel.

The XLA sqrt-N path (``core/sqrtn._eval_contract_batched_jit``) scans
``[B, rc, K]`` PRF grid slabs through HBM: every scan step materializes
the slab, applies the LSB codeword select/add, and hands ``matmul128``
a ``[B, rc*K]`` leaf-share tensor — at ChaCha's ~25 int-ops/byte that
slab traffic is comparable to the compute.  This module supplies the
fused alternative (the sqrt-N half of the ROADMAP megakernel item,
completing ``pallas_level.subtree_contract_pallas``'s logn half):

grid ``(B/TB, R/rc)`` — for each key tile, one ``rc``-row tile of the
``[R, K]`` PRF grid is expanded **entirely in VMEM** (one cipher call
over the ``[TB, rc*K]`` cell planes; the block-PRG ids evaluate one
512-bit core block per FOUR grid rows and interleave, exactly
``sqrtn._grid_vals``), the low-limb codeword select/add lands in
registers, and the ``[TB, E]`` table contraction accumulates in the
VMEM-resident output block (the documented reduction-dim pattern: the
innermost grid dimension does not appear in the output index map).  The
one-hot leaf share never touches HBM.

Cell order is natural: cell ``m = t*K + c`` of a tile holds grid row
``row0 + t``, column seed ``c`` — table rows line up with no
permutation, and a traced ``row0`` (the sharded path's per-shard row
base) rides in as a tiny ``[steps, 1]`` VMEM operand.

Only the low 32 output bits are contracted, and 128-bit adds carry
upward only, so the codeword add needs just the low limb — the kernel
ships ``cw*[..., 0]`` planes and skips the carry chain entirely.

**Kernel variants** (the generative-search space, ``tune/
kernel_search.py``): the structural choices PR 10 hard-coded are now
parameters — ``tb`` (key-tile height), ``max_cells`` (the VMEM cell
budget the row chunk halves down to), ``grid_order`` ("bk" = key tiles
outer / row tiles inner, the reduction-dim default; "kb" = row tiles
outer, valid only when one key tile covers the batch — revisiting an
output block from non-adjacent grid steps is not Mosaic-legal),
``dim_semantics`` (the KEY-tile axis as "parallel" or "arbitrary"; the
row axis accumulates and is always "arbitrary"), ``limbs`` ("low" =
low-limb-only codeword add; "multi" = all four value limbs + the full
128-bit carry chain, the scan path's exact arithmetic — bit-identical
because carries only propagate upward), and ``cw_add`` ("fused" = the
``jnp.where`` select; "staged" = base-add-then-masked-correction,
``cw1 + sel*(cw2-cw1)``, bit-identical mod 2^32).  Every variant is
equality-gated against the scan oracle before it is ever trusted.

Correctness: asserted against the scan-path oracle in tests (interpret
mode on CPU, compiled on TPU).  ChaCha20-12/Salsa20-12 cores and their
block-PRG variants; AES stays on the XLA path (see
``pallas_level``'s module docstring).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .pallas_level import (_BLK_CORES, _CORES, _add128_planes,
                           _compiler_params)

# default tile knobs: widest live state = 16 cipher words x [TB, cells]
# u32 (the block-PRG ids quarter that — one block per 4 rows).  These
# are the PR-10 hand-tuned values; the kernel search treats them as the
# seed of the variant space, not the answer.
PALLAS_SQRT_TB = 32         # key tile (sublane-friendly multiple of 8)
PALLAS_SQRT_MAX_CELLS = 2048  # rc*K per tile -> ~4 MB cipher state


def pallas_sqrt_unsupported(prf_method: int, r: int) -> str | None:
    """Why the grid kernel cannot run this shape (None = it can).

    Callers that resolved ``kernel_impl="pallas"`` degrade to the xla
    scan path with provenance (``note_swallowed``) instead of raising —
    only an EXPLICIT pallas pin surfaces the reason as an error."""
    if prf_method not in _CORES and prf_method not in _BLK_CORES:
        return ("prf id %d has no Pallas plane core (AES stays on the "
                "XLA dispatch path)" % prf_method)
    if prf_method in _BLK_CORES and r % 4:
        return ("block-PRG sqrt-N grid kernel needs R (%d) to be a "
                "multiple of 4 (the 4-rows-per-core-block interleave "
                "cannot straddle a tile edge)" % r)
    return None


def pallas_sqrt_row_chunk(r: int, k: int, row_chunk: int | None = None,
                          max_cells: int | None = None) -> int:
    """Grid rows per kernel step.  The kernel's live state is the
    ``[TB, rc*K]`` cipher planes in VMEM, so the bound is the CELL count
    (``max_cells``, default ``PALLAS_SQRT_MAX_CELLS``), not the XLA
    scan's 64 MiB HBM slab.  Explicit/tuned values obey the shared
    row-chunk rules (divide R, multiple of 4 when chunking —
    ``sqrtn._resolve_row_chunk``) and are then halved down to the cell
    cap: the accumulation order changes, the bits do not (int32 adds
    wrap).  That halving used to be silent — callers that need to know
    whether the kernel they dispatch matches the chunk their cache
    entry claims compare this function's answer against the request
    (``api``'s ``row_chunk_effective`` provenance)."""
    from ..core.sqrtn import ROW_CHUNK_FLOOR, _resolve_row_chunk
    cap = PALLAS_SQRT_MAX_CELLS if max_cells is None else int(max_cells)
    rc = r if row_chunk is None else _resolve_row_chunk(r, k, 1, row_chunk)
    # halving preserves "divides R"; the %8 guard keeps rc a multiple
    # of 4 all the way down to the 4-row interleave floor
    while rc * k > cap and rc > ROW_CHUNK_FLOOR and rc % 8 == 0:
        rc //= 2
    return rc


def _make_sqrt_kernel(prf_method: int, tb: int, rc: int, k: int,
                      j_axis: int = 1, limbs: str = "low",
                      cw_add: str = "fused"):
    """Kernel body for one (key tile, row tile) grid step.

    ``j_axis``: which grid axis is the row-tile (accumulation) axis.
    ``limbs``/``cw_add``: emission and codeword-select structure (see
    the module docstring); every combination is bit-identical.
    """
    from jax.experimental import pallas as pl

    blk = _BLK_CORES.get(prf_method)
    core = None if blk is not None else _CORES[prf_method]
    cells = rc * k
    nlimb = 4 if limbs == "multi" else 1

    def tile(p):
        """[TB, rc, K]-broadcast -> [TB, cells] cell plane."""
        return jnp.broadcast_to(p, (tb, rc, k)).reshape(tb, cells)

    def kernel(row0_ref, seeds_ref, cw1_ref, cw2_ref, table_ref, out_ref):
        j = pl.program_id(j_axis)
        row0 = row0_ref[0, 0]                          # this tile's base row
        s = [seeds_ref[i] for i in range(4)]           # [TB, K]
        # cell m = t*K + c: grid row row0+t under column seed c —
        # natural order, matching the table tile rows directly
        if blk is not None:
            # ONE core block per 4 grid rows: counter plane c for rows
            # 4c..4c+3 (row0 is a multiple of 4 by the row-chunk rules)
            nctr = rc // 4
            planes = [jnp.broadcast_to(p[:, None, :], (tb, nctr, k))
                      .reshape(tb, nctr * k) for p in s]
            ctr = ((row0 >> np.uint32(2))
                   + lax.broadcasted_iota(jnp.uint32, (tb, nctr, k), 1)
                   .reshape(tb, nctr * k))
            out16 = blk(planes, ctr)
            # row 4c+g = block words [4g..4g+3] MSW-first, so limb l of
            # that row is word 4g+3-l (``_grid_vals``/``_blk_group``)
            vals = [jnp.stack([out16[4 * g + 3 - l].reshape(tb, nctr, k)
                               for g in range(4)],
                              axis=2).reshape(tb, cells)
                    for l in range(nlimb)]
        else:
            planes = [tile(p[:, None, :]) for p in s]
            pos = (row0 + lax.broadcasted_iota(jnp.uint32, (tb, rc, k), 1)
                   .reshape(tb, cells))
            vals = list(core(planes, pos)[:nlimb])
        sel = (s[0] & np.uint32(1))                    # [TB, K] u32 0/1

        def select(c1, c2):
            """The codeword the LSB picks, as a [TB, cells] plane."""
            if cw_add == "staged":
                # base + masked correction: cw1 + sel*(cw2-cw1), exact
                # mod 2^32 (u32 wraps) — two staged adds, no select op
                return tile(c1[:, :, None]) + tile(sel[:, None, :]) * \
                    tile((c2 - c1)[:, :, None])
            return jnp.where(tile(sel.astype(jnp.bool_)[:, None, :]),
                             tile(c2[:, :, None]), tile(c1[:, :, None]))

        if limbs == "multi":
            # the scan path's exact arithmetic: all four value limbs +
            # the full 128-bit carry chain, low limb contracted (carries
            # only propagate upward, so the bits match the low-only path)
            cw = [select(cw1_ref[..., l], cw2_ref[..., l])
                  for l in range(4)]
            leaves = _add128_planes(vals, cw)[0].astype(jnp.int32)
        else:
            leaves = (vals[0] + select(cw1_ref[:], cw2_ref[:])) \
                .astype(jnp.int32)                     # [TB, cells]
        contrib = lax.dot_general(
            leaves, table_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)          # x [E, cells]

        @pl.when(j == 0)
        def _():
            out_ref[:] = contrib

        @pl.when(j > 0)
        def _():
            out_ref[:] = out_ref[:] + contrib

    return kernel


def _sqrt_grid_contract_impl(seeds, cw1, cw2, table, row0, *,
                             prf_method: int, row_chunk: int | None = None,
                             interpret=False, tb: int | None = None,
                             max_cells: int | None = None,
                             grid_order: str = "bk",
                             dim_semantics: str = "parallel",
                             limbs: str = "low", cw_add: str = "fused"):
    """Traceable launcher (the sharded per-shard body calls this inside
    its own jit/shard_map with a TRACED ``row0``).

    seeds: [B, K, 4] u32; cw1/cw2: [B, R, 4] u32; table: [R*K, E] int32
    natural-order rows for grid rows row0..row0+R-1.  Returns [B, E]
    int32 shares, bit-identical to the scan oracle for EVERY variant of
    (tb, max_cells, grid_order, dim_semantics, limbs, cw_add).
    """
    from jax.experimental import pallas as pl

    bsz, k, _ = seeds.shape
    r = cw1.shape[1]
    e = table.shape[1]
    assert table.shape[0] == r * k, (table.shape, r, k)
    reason = pallas_sqrt_unsupported(prf_method, r)
    if reason:
        raise ValueError(reason)
    if grid_order not in ("bk", "kb"):
        raise ValueError("grid_order must be 'bk' or 'kb' (got %r)"
                         % (grid_order,))
    if dim_semantics not in ("parallel", "arbitrary"):
        raise ValueError("dim_semantics must be 'parallel' or "
                         "'arbitrary' (got %r)" % (dim_semantics,))
    if limbs not in ("low", "multi"):
        raise ValueError("limbs must be 'low' or 'multi' (got %r)"
                         % (limbs,))
    if cw_add not in ("fused", "staged"):
        raise ValueError("cw_add must be 'fused' or 'staged' (got %r)"
                         % (cw_add,))
    rc = pallas_sqrt_row_chunk(r, k, row_chunk, max_cells)
    steps = r // rc

    tb = tb or min(PALLAS_SQRT_TB, max(8, bsz))
    pb = (-bsz) % tb
    if pb:
        seeds = jnp.pad(seeds, ((0, pb), (0, 0), (0, 0)))
        cw1 = jnp.pad(cw1, ((0, pb), (0, 0), (0, 0)))
        cw2 = jnp.pad(cw2, ((0, pb), (0, 0), (0, 0)))
    bp = bsz + pb
    if grid_order == "kb" and bp > tb:
        # rows-outer revisits each output block from NON-adjacent grid
        # steps once there is more than one key tile — not Mosaic-legal
        # (the searcher's validity predicate mirrors this rule)
        raise ValueError(
            "grid_order='kb' needs the batch (%d padded) to fit one "
            "key tile (tb=%d): rows-outer iteration would revisit "
            "output blocks non-consecutively" % (bp, tb))

    sm = jnp.transpose(seeds, (2, 0, 1))               # [4, B, K]
    if limbs == "multi":
        cw1_in, cw2_in = cw1, cw2                      # [B, R, 4] full
        cw_spec = lambda im: pl.BlockSpec((tb, rc, 4), im)  # noqa: E731
        cw_maps = (lambda i, j: (i, j, 0)), (lambda j, i: (i, j, 0))
    else:
        cw1_in, cw2_in = cw1[:, :, 0], cw2[:, :, 0]    # [B, R] low limbs
        cw_spec = lambda im: pl.BlockSpec((tb, rc), im)  # noqa: E731
        cw_maps = (lambda i, j: (i, j)), (lambda j, i: (i, j))
    table_t = table.T                                  # [E, R*K]
    row0s = (jnp.asarray(row0, jnp.uint32)
             + jnp.arange(steps, dtype=jnp.uint32)
             * jnp.uint32(rc))[:, None]                # [steps, 1]

    if grid_order == "bk":
        grid = (bp // tb, steps)
        j_axis, cw_map = 1, cw_maps[0]
        maps = (lambda i, j: (j, 0),          # row0s
                lambda i, j: (0, i, 0),       # seeds
                lambda i, j: (0, j),          # table
                lambda i, j: (i, 0))          # out
        semantics = (dim_semantics, "arbitrary")
    else:
        grid = (steps, bp // tb)
        j_axis, cw_map = 0, cw_maps[1]
        maps = (lambda j, i: (j, 0),
                lambda j, i: (0, i, 0),
                lambda j, i: (0, j),
                lambda j, i: (i, 0))
        semantics = ("arbitrary", dim_semantics)

    kernel = _make_sqrt_kernel(prf_method, tb, rc, k, j_axis=j_axis,
                               limbs=limbs, cw_add=cw_add)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), maps[0]),
            pl.BlockSpec((4, tb, k), maps[1]),
            cw_spec(cw_map),
            cw_spec(cw_map),
            pl.BlockSpec((e, rc * k), maps[2]),
        ],
        out_specs=pl.BlockSpec((tb, e), maps[3]),
        out_shape=jax.ShapeDtypeStruct((bp, e), jnp.int32),
        interpret=interpret,
        # key tiles are independent; the row-tile axis accumulates into
        # the same [tb, E] output block (reduction dim -> "arbitrary")
        compiler_params=_compiler_params(semantics),
    )(row0s, sm, cw1_in, cw2_in, table_t)
    return out[:bsz]


_VARIANT_FIELDS = ("tb", "max_cells", "grid_order", "dim_semantics",
                   "limbs", "cw_add")

_sqrt_grid_contract_jit = functools.partial(
    jax.jit, static_argnames=("prf_method", "row_chunk", "interpret")
    + _VARIANT_FIELDS)(_sqrt_grid_contract_impl)


def sqrt_grid_contract_pallas(seeds, cw1, cw2, table, *, prf_method: int,
                              row_chunk: int | None = None, row0=0,
                              interpret=False, tb: int | None = None,
                              max_cells: int | None = None,
                              grid_order: str = "bk",
                              dim_semantics: str = "parallel",
                              limbs: str = "low", cw_add: str = "fused"):
    """Jit-wrapped fused sqrt-N grid kernel; ``interpret=True`` runs
    EAGERLY (see ``pallas_level.chacha_level_step_pallas`` —
    interpret-under-jit compile blows up super-linearly on XLA-CPU).

    ``row0`` may be a traced uint32 scalar (the sharded path's
    per-shard row base); already-traced callers get the impl inlined.
    The variant keywords default to the PR-10 hand-tuned structure; the
    kernel search (``tune/kernel_search.py``) threads searched values
    through here.
    """
    args = (jnp.asarray(seeds), jnp.asarray(cw1), jnp.asarray(cw2),
            jnp.asarray(table), row0)
    fn = (_sqrt_grid_contract_impl if interpret
          else _sqrt_grid_contract_jit)
    return fn(*args, prf_method=prf_method, row_chunk=row_chunk,
              interpret=interpret, tb=tb, max_cells=max_cells,
              grid_order=grid_order, dim_semantics=dim_semantics,
              limbs=limbs, cw_add=cw_add)
