"""Exact mod-2^32 matmul strategies for the fused DPF contraction.

The server-side contraction is ``out[b,e] = sum_j leaf32[b,j] * table[j,e]
(mod 2^32)`` (see core/expand.py for why mod 2^32 suffices — the reference
instead runs a custom 128-bit split-K GEMM, ``dpf_gpu/matmul/matmul.cu``).

Two implementations:

* ``dot_i32`` — single ``dot_general`` on int32.  XLA TPU executes integer
  dots on the VPU; exact, simple, and fine when the PRF dominates.
* ``dot_i32_mxu`` — byte-limb decomposition onto the MXU's native
  int8 x int8 -> int32 path: split both operands into 4 unsigned byte limbs,
  keep the 10 limb-pair products with shift < 32, run them as int8 matmuls
  (values biased by -128 into int8 range, corrected with rank-1 terms), and
  recombine with wrapping shifts.  int32 accumulator overflow is harmless —
  wrapping is exactly mod-2^32 semantics.

Both are bit-exact; expand.py picks via ``set_dot_impl`` after benchmarking.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax


def dot_i32(a, b):
    """[B, K] x [K, E] -> [B, E], wrapping int32."""
    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)


def _byte_limbs_signed(x):
    """int32 [M, N] -> list of 4 int8 arrays, limb k holding byte k - 128.

    Returns (limbs, sums) where sums[k] is the per-row (axis kept) int32 sum
    of the *unsigned* byte limb, needed for the bias correction.
    """
    xu = lax.bitcast_convert_type(x, jnp.uint32)
    limbs = []
    usums = []
    for k in range(4):
        byte = (xu >> np.uint32(8 * k)) & np.uint32(0xFF)  # [M, N] in 0..255
        byte_i32 = byte.astype(jnp.int32)
        limbs.append((byte_i32 - 128).astype(jnp.int8))
        usums.append(byte_i32)
    return limbs, usums


def dot_i32_mxu(a, b):
    """MXU-decomposed exact wrapping int32 matmul: [B, K] x [K, E]."""
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    k_dim = a.shape[1]
    a_limbs, a_bytes = _byte_limbs_signed(a)
    b_limbs, b_bytes = _byte_limbs_signed(b)
    # bias corrections: for u = s + 128,
    #   U_a @ U_b = S_a@S_b + 128*rowsum(S_a) + 128*colsum(S_b) + 128^2*K
    # with rowsum/colsum of the SIGNED limbs; compute from unsigned sums:
    #   rowsum(S_a) = rowsum(U_a) - 128*K
    a_rowsums = [s.sum(axis=1, keepdims=True) - 128 * k_dim
                 for s in a_bytes]                        # [B, 1] int32
    b_colsums = [s.sum(axis=0, keepdims=True) - 128 * k_dim
                 for s in b_bytes]                        # [1, E] int32
    bias_const = np.uint32((128 * 128 * k_dim)
                           & 0xFFFFFFFF).astype(np.int32)

    out = jnp.zeros((a.shape[0], b.shape[1]), dtype=jnp.int32)
    for i in range(4):
        for j in range(4 - i):
            prod = lax.dot_general(a_limbs[i], b_limbs[j],
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.int32)
            term = (prod + 128 * a_rowsums[i] + 128 * b_colsums[j]
                    + bias_const)
            out = out + (term << np.int32(8 * (i + j)))
    return out


IMPLS = {"i32": dot_i32, "mxu": dot_i32_mxu}

_DEFAULT_IMPL = "i32"


def available_impls() -> tuple:
    """Registered contraction backends, in registry order — the
    autotuner's ``dot_impl`` candidate list.  Every member is bit-exact
    mod 2^32 (test_ops.py), so the tuner may flip between them freely."""
    return tuple(IMPLS)


def register_impl(name: str, fn) -> None:
    """Add a contraction backend to the registry (and thus to the
    autotuner's search space).  ``fn(a, b)`` must be an exact wrapping
    int32 matmul — the tuner's equality gate will reject it per shape
    otherwise, but registering a non-exact impl is still a bug."""
    IMPLS[name] = fn


def set_dot_impl(name: str):
    """Select the default contraction backend: "i32" or "mxu".

    The choice is threaded into jitted programs as a *static* argument
    (see expand.expand_and_contract), so changing it here retraces —
    already-compiled executables are never silently stale."""
    global _DEFAULT_IMPL
    if name not in IMPLS:
        raise KeyError(name)
    _DEFAULT_IMPL = name


def default_impl() -> str:
    return _DEFAULT_IMPL


def dot(a, b, impl: str | None = None):
    return IMPLS[impl or _DEFAULT_IMPL](a, b)
