"""Experimental Pallas TPU kernel for the GGM level step (ChaCha20-12).

The default expansion path relies on XLA fusing the unrolled cipher rounds
into VPU pipelines (see docs/PERFORMANCE.md — at ~25 int-ops/byte the level
step is solidly compute-bound, so fusion should reach the roofline).  This
kernel is the hand-scheduled alternative for A/B measurement: one
``pallas_call`` computes both children of every node with all 12 rounds
resident in VMEM, fused with the codeword-select-add — no intermediate HBM
traffic even if XLA's fusion heuristics decline.

Layout: the kernel works limb-major ([4, B, w] — lanes along the wide node
axis); the [B, w, 4] <-> limb-major transposes sit at the kernel boundary
inside jit where they are negligible next to the cipher.

Correctness is asserted against the portable path in tests (interpret mode
on CPU; compiled on TPU).  Only ChaCha20-12 for now — the PRF with the
best measured throughput profile; extending to Salsa is mechanical.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core.prf import _SIGMA


def _rotl(x, b):
    return (x << np.uint32(b)) | (x >> np.uint32(32 - b))


def _chacha_pair_kernel(seeds_ref, cw1_ref, cw2_ref, out0_ref, out1_ref):
    """seeds [4, TB, TW] u32; cw* [4, TB, 2] u32 (limb, key, branch);
    out* [4, TB, TW] u32 — children for branch 0 and 1."""
    s = [seeds_ref[i] for i in range(4)]        # [TB, TW] each

    def core(pos_word):
        zero = s[0] - s[0]
        x = [zero + np.uint32(_SIGMA[i]) for i in range(4)]
        x += [s[3], s[2], s[1], s[0]]
        x += [zero] * 4
        x += [zero, zero + np.uint32(pos_word), zero, zero]
        init = list(x)
        for _ in range(6):
            for (a, b, c, d) in ((0, 4, 8, 12), (1, 5, 9, 13),
                                 (2, 6, 10, 14), (3, 7, 11, 15),
                                 (0, 5, 10, 15), (1, 6, 11, 12),
                                 (2, 7, 8, 13), (3, 4, 9, 14)):
                x[a] = x[a] + x[b]
                x[d] = _rotl(x[d] ^ x[a], 16)
                x[c] = x[c] + x[d]
                x[b] = _rotl(x[b] ^ x[c], 12)
                x[a] = x[a] + x[b]
                x[d] = _rotl(x[d] ^ x[a], 8)
                x[c] = x[c] + x[d]
                x[b] = _rotl(x[b] ^ x[c], 7)
        # output words 4..7 MSW-first -> limbs LSW-first
        return [x[7] + init[7], x[6] + init[6], x[5] + init[5],
                x[4] + init[4]]

    sel = (s[0] & np.uint32(1)).astype(jnp.bool_)   # [TB, TW]
    for branch, out_ref in ((0, out0_ref), (1, out1_ref)):
        val = core(np.uint32(branch))
        carry = None
        for i in range(4):
            cw_i = jnp.where(sel, cw2_ref[i, :, branch][:, None],
                             cw1_ref[i, :, branch][:, None])
            t = val[i] + cw_i
            c1 = (t < val[i]).astype(jnp.uint32)
            if carry is None:
                out_ref[i] = t
                carry = c1
            else:
                t2 = t + carry
                c2 = (t2 < t).astype(jnp.uint32)
                out_ref[i] = t2
                carry = c1 | c2


@functools.partial(jax.jit, static_argnames=("interpret",))
def chacha_level_step_pallas(seeds, cw1_lvl, cw2_lvl, interpret=False):
    """One ChaCha GGM level via Pallas.

    seeds: [B, w, 4] u32; cw*_lvl: [B, 2, 4] u32 (this level's codeword
    pair per key).  Returns [B, 2w, 4] children (new[2j+b] layout).
    """
    from jax.experimental import pallas as pl

    bsz, w, _ = seeds.shape
    sm = jnp.transpose(seeds, (2, 0, 1))            # [4, B, w]
    cw1 = jnp.transpose(cw1_lvl, (2, 0, 1))         # [4, B, 2]
    cw2 = jnp.transpose(cw2_lvl, (2, 0, 1))

    out_shape = [jax.ShapeDtypeStruct((4, bsz, w), jnp.uint32)] * 2
    out0, out1 = pl.pallas_call(
        _chacha_pair_kernel,
        out_shape=out_shape,
        interpret=interpret,
    )(sm, cw1, cw2)

    children = jnp.stack([jnp.transpose(out0, (1, 2, 0)),
                          jnp.transpose(out1, (1, 2, 0))], axis=2)
    return children.reshape(bsz, 2 * w, 4)
