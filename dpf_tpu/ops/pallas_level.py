"""Hand-scheduled Pallas TPU kernels for the GGM expansion hot path.

The XLA path (``core/expand.py``) relies on fusion for the cipher rounds
but pays HBM round-trips for the ``[B, w, 4]`` seed tensors between tree
levels (the ``lax.scan`` carry).  At ChaCha's ~25 int-ops/byte that
traffic is comparable to the compute, so a fused kernel has up to ~2x of
headroom.  This module supplies the hand-scheduled alternative — the role
the reference's tuned hybrid kernel plays on GPU
(``dpf_gpu/dpf/dpf_hybrid.cu:123-231``, DFS subtrees resident in shared
memory) — redesigned for the TPU memory hierarchy:

* ``subtree_contract_pallas`` — the production kernel.  Grid
  ``(B/TB, F)``: for each key tile, every frontier subtree is expanded
  root-to-leaves **entirely in VMEM** (no inter-level HBM traffic), the
  low-32 leaf shares are contracted against the matching table chunk, and
  the ``[TB, E]`` accumulator stays resident in VMEM across the chunk
  axis (the documented reduction-dim pattern: the innermost grid
  dimension does not appear in the output index map).
* ``chacha_level_step_pallas`` — a single tiled level step (kept for
  layer-by-layer A/B measurement), grid over ``(B, w)`` tiles so VMEM
  stays bounded at any width.

Layout: limb-major ``[4, B, w]`` — the wide node axis rides the 128-wide
lanes; the ``[B, w, 4]`` boundary transposes sit inside jit where they are
negligible next to the cipher.

Correctness: asserted against the portable XLA path in tests (interpret
mode on CPU, compiled on TPU).  ChaCha20-12 and Salsa20-12 cores; the
bitsliced-AES variant stays on the XLA dispatch path (its pack/unpack
transposes do not benefit from manual scheduling).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.prf import _SIGMA


def _compiler_params(dimension_semantics):
    """Mosaic grid-dimension semantics ("parallel" dims may be pipelined
    /parallelized; "arbitrary" = sequential, for accumulation dims).
    Returns None when the running jax has no CompilerParams (interpret
    engines ignore it anyway)."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:
        return None
    for name in ("CompilerParams", "TPUCompilerParams"):  # new/old spelling
        try:
            return getattr(pltpu, name)(
                dimension_semantics=dimension_semantics)
        except (AttributeError, TypeError):
            continue
    return None


def _rotl(x, b):
    return (x << np.uint32(b)) | (x >> np.uint32(32 - b))


def _pos_plane(zero, pos_word):
    """The cipher state's position word as a plane.  Scalar positions
    (the GGM child index) stay a hard-coded u32 constant; array
    positions (the sqrt-N grid kernel's per-cell row counters,
    ``ops/pallas_sqrt.py``) broadcast against the zero plane."""
    if isinstance(pos_word, (int, np.integer)):
        return zero + np.uint32(pos_word)
    return zero + pos_word


def _chacha_block_planes(s, pos_word):
    """ChaCha20-12 full block on 4 seed planes -> 16 output words.

    Key/position placement matches ``core/prf._chacha_state`` (seed limbs
    LSW-first occupy state words 7..4) so results are bit-identical to
    the portable path.  The 6 double rounds run in a ``lax.fori_loop``:
    a fully unrolled body, chained across subtree levels through the
    block's constant-initialized output words, sends the XLA CPU
    simplifier into a pathological slow compile (hours at depth 6); the
    loop form compiles in seconds on every backend and Mosaic handles
    static-trip-count loops natively.
    """
    zero = s[0] - s[0]
    x = [zero + np.uint32(_SIGMA[i]) for i in range(4)]
    x += [s[3], s[2], s[1], s[0]]
    x += [zero] * 4
    x += [zero, _pos_plane(zero, pos_word), zero, zero]
    init = jnp.stack(x)

    def double_round(_, st):
        x = [st[i] for i in range(16)]
        for (a, b, c, d) in ((0, 4, 8, 12), (1, 5, 9, 13),
                             (2, 6, 10, 14), (3, 7, 11, 15),
                             (0, 5, 10, 15), (1, 6, 11, 12),
                             (2, 7, 8, 13), (3, 4, 9, 14)):
            x[a] = x[a] + x[b]
            x[d] = _rotl(x[d] ^ x[a], 16)
            x[c] = x[c] + x[d]
            x[b] = _rotl(x[b] ^ x[c], 12)
            x[a] = x[a] + x[b]
            x[d] = _rotl(x[d] ^ x[a], 8)
            x[c] = x[c] + x[d]
            x[b] = _rotl(x[b] ^ x[c], 7)
        return jnp.stack(x)

    out = lax.fori_loop(0, 6, double_round, init) + init
    return [out[i] for i in range(16)]


def _chacha_core_planes(s, pos_word):
    """ChaCha20-12 core -> 4 output planes (words 7..4, limbs LSW-first)."""
    o = _chacha_block_planes(s, pos_word)
    return [o[7], o[6], o[5], o[4]]


def _salsa_block_planes(s, pos_word):
    """Salsa20-12 full block — layout matches ``core/prf._salsa_state``
    (key at words 4..1 LSW-last, pos at word 9).  fori_loop rounds for
    the same compile-pathology reason as ``_chacha_block_planes``."""
    zero = s[0] - s[0]
    x = [zero] * 16
    x[0] = zero + np.uint32(_SIGMA[0])
    x[5] = zero + np.uint32(_SIGMA[1])
    x[10] = zero + np.uint32(_SIGMA[2])
    x[15] = zero + np.uint32(_SIGMA[3])
    x[1], x[2], x[3], x[4] = s[3], s[2], s[1], s[0]
    x[9] = _pos_plane(zero, pos_word)
    init = jnp.stack(x)

    def double_round(_, st):
        x = [st[i] for i in range(16)]
        for (a, b, c, d) in ((0, 4, 8, 12), (5, 9, 13, 1), (10, 14, 2, 6),
                             (15, 3, 7, 11), (0, 1, 2, 3), (5, 6, 7, 4),
                             (10, 11, 8, 9), (15, 12, 13, 14)):
            x[b] = x[b] ^ _rotl(x[a] + x[d], 7)
            x[c] = x[c] ^ _rotl(x[b] + x[a], 9)
            x[d] = x[d] ^ _rotl(x[c] + x[b], 13)
            x[a] = x[a] ^ _rotl(x[d] + x[c], 18)
        return jnp.stack(x)

    out = lax.fori_loop(0, 6, double_round, init) + init
    return [out[i] for i in range(16)]


def _salsa_core_planes(s, pos_word):
    """Salsa20-12 core -> 4 output planes (words 4..1, limbs LSW-first)."""
    o = _salsa_block_planes(s, pos_word)
    return [o[4], o[3], o[2], o[1]]


_CORES = {2: _chacha_core_planes, 1: _salsa_core_planes}  # prf id -> core
# block-PRG ids (core/prf_ref.py): ONE core call per node feeds all
# children — child b = block words [4b..4b+3] MSW-first, i.e. planes
# (limbs LSW-first) [4b+3, 4b+2, 4b+1, 4b]
_BLK_CORES = {4: _salsa_block_planes, 5: _chacha_block_planes}


def _add128_planes(val, cw):
    """val + cw mod 2^128 on two 4-plane lists (explicit carry chain)."""
    out = []
    carry = None
    for i in range(4):
        t = val[i] + cw[i]
        c1 = (t < val[i]).astype(jnp.uint32)
        if carry is None:
            out.append(t)
            carry = c1
        else:
            t2 = t + carry
            c2 = (t2 < t).astype(jnp.uint32)
            out.append(t2)
            carry = c1 | c2
    return out


# ---------------------------------------------------------------------------
# Tiled single level step
# ---------------------------------------------------------------------------

def _level_kernel(seeds_ref, cw1_ref, cw2_ref, out0_ref, out1_ref):
    """seeds [4, TB, TW] u32; cw* [4, TB, 2] (limb, key, branch);
    out* [4, TB, TW] — children for branches 0 and 1."""
    s = [seeds_ref[i] for i in range(4)]
    sel = (s[0] & np.uint32(1)).astype(jnp.bool_)
    for branch, out_ref in ((0, out0_ref), (1, out1_ref)):
        val = _chacha_core_planes(s, np.uint32(branch))
        cw = [jnp.where(sel, cw2_ref[i, :, branch][:, None],
                        cw1_ref[i, :, branch][:, None]) for i in range(4)]
        res = _add128_planes(val, cw)
        for i in range(4):
            out_ref[i] = res[i]


def _chacha_level_step_impl(seeds, cw1_lvl, cw2_lvl, interpret=False,
                            tb: int = 8, tw: int = 512):
    """One ChaCha GGM level via Pallas, tiled over (batch, width).

    seeds: [B, w, 4] u32; cw*_lvl: [B, 2, 4] u32 (this level's codeword
    pair per key).  Returns [B, 2w, 4] children (new[2j+b] layout).
    VMEM per step is bounded by the (tb, tw) tile regardless of B, w.
    """
    from jax.experimental import pallas as pl

    bsz, w, _ = seeds.shape
    tb = min(tb, bsz)
    tw = min(tw, w)
    if bsz % tb or w % tw:  # pad to tile multiples, slice after
        pb = (-bsz) % tb
        pw = (-w) % tw
        seeds = jnp.pad(seeds, ((0, pb), (0, pw), (0, 0)))
        cw1_lvl = jnp.pad(cw1_lvl, ((0, pb), (0, 0), (0, 0)))
        cw2_lvl = jnp.pad(cw2_lvl, ((0, pb), (0, 0), (0, 0)))
    bp, wp = seeds.shape[0], seeds.shape[1]

    sm = jnp.transpose(seeds, (2, 0, 1))     # [4, B, w]
    cw1 = jnp.transpose(cw1_lvl, (2, 0, 1))  # [4, B, 2]
    cw2 = jnp.transpose(cw2_lvl, (2, 0, 1))

    grid = (bp // tb, wp // tw)
    out_shape = [jax.ShapeDtypeStruct((4, bp, wp), jnp.uint32)] * 2
    spec_seeds = pl.BlockSpec((4, tb, tw), lambda i, j: (0, i, j))
    spec_cw = pl.BlockSpec((4, tb, 2), lambda i, j: (0, i, 0))
    spec_out = pl.BlockSpec((4, tb, tw), lambda i, j: (0, i, j))
    out0, out1 = pl.pallas_call(
        _level_kernel,
        grid=grid,
        compiler_params=_compiler_params(("parallel", "parallel")),
        in_specs=[spec_seeds, spec_cw, spec_cw],
        out_specs=[spec_out, spec_out],
        out_shape=out_shape,
        interpret=interpret,
    )(sm, cw1, cw2)

    children = jnp.stack([jnp.transpose(out0, (1, 2, 0)),
                          jnp.transpose(out1, (1, 2, 0))], axis=2)
    return children.reshape(bp, 2 * wp, 4)[:bsz, :2 * w]


_chacha_level_step_jit = functools.partial(
    jax.jit, static_argnames=("interpret", "tb", "tw"))(
        _chacha_level_step_impl)


def chacha_level_step_pallas(seeds, cw1_lvl, cw2_lvl, interpret=False,
                             tb: int = 8, tw: int = 512):
    """Jit-wrapped level step; ``interpret=True`` runs EAGERLY.

    XLA-CPU compile of an interpret-mode pallas_call grows super-linearly
    with grid size (a 2x2 grid was observed past 30 GB / 20 min of
    compile); eager interpret executes the kernel body op-by-op in
    seconds.  Only the compiled (TPU) path needs the jit.
    """
    if interpret:
        return _chacha_level_step_impl(seeds, cw1_lvl, cw2_lvl,
                                       interpret=True, tb=tb, tw=tw)
    return _chacha_level_step_jit(seeds, cw1_lvl, cw2_lvl,
                                  interpret=False, tb=tb, tw=tw)


# ---------------------------------------------------------------------------
# Fused subtree expand + contract (the production kernel)
# ---------------------------------------------------------------------------

def _make_subtree_kernel(sched: tuple, prf_method: int = 2):
    """Kernel over a per-level arity schedule.  ``sched[k]`` is the
    fan-out of kernel level k; the sliced codeword arrays hold the levels'
    slots back to back in the same order (see the wrapper's ``idx``).
    Block-PRG methods evaluate ONE core per node per level and split the
    512-bit block into the children (4x fewer cores at arity 4)."""
    from jax.experimental import pallas as pl

    blk = _BLK_CORES.get(prf_method)
    core = None if blk is not None else _CORES[prf_method]

    def kernel(seeds_ref, cw1_ref, cw2_ref, table_ref, out_ref):
        f = pl.program_id(1)
        planes = [seeds_ref[i] for i in range(4)]     # [TB, 1]
        off = 0
        for a in sched:
            sel = (planes[0] & np.uint32(1)).astype(jnp.bool_)  # [TB, w]
            if blk is not None:
                out16 = blk(planes, np.uint32(0))
            children = []
            for b in range(a):
                if blk is not None:
                    val = [out16[4 * b + 3], out16[4 * b + 2],
                           out16[4 * b + 1], out16[4 * b]]
                else:
                    val = core(planes, np.uint32(b))
                cw = [jnp.where(sel, cw2_ref[i, :, off + b][:, None],
                                cw1_ref[i, :, off + b][:, None])
                      for i in range(4)]
                children.append(_add128_planes(val, cw))
            off += a
            w = planes[0].shape[1]
            planes = [jnp.stack([children[b][i] for b in range(a)],
                                axis=2).reshape(-1, a * w)
                      for i in range(4)]
        leaves = planes[0].astype(jnp.int32)          # [TB, C]
        contrib = lax.dot_general(
            leaves, table_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)         # x [E, C] -> [TB, E]

        @pl.when(f == 0)
        def _():
            out_ref[:] = contrib

        @pl.when(f > 0)
        def _():
            out_ref[:] = out_ref[:] + contrib

    return kernel


# default tile knobs: widest level state = 16 words x [TB, C/2] u32
PALLAS_TB = 32       # key tile (sublane-friendly multiple of 8)
PALLAS_MAX_C = 4096  # leaves per subtree -> ~4 MB cipher state in VMEM


def _subtree_contract_run(frontier, cw1, cw2, table_perm, *, idx, sched,
                          prf_method, interpret, tb):
    """Shared launcher: slice codeword slots (``idx``, level-major), pad
    the batch to the key-tile multiple, run the schedule kernel."""
    from jax.experimental import pallas as pl

    bsz, f_cnt, _ = frontier.shape
    n, e = table_perm.shape
    c = n // f_cnt
    assert c == int(np.prod(sched)), (c, sched)

    tb = tb or min(PALLAS_TB, max(8, bsz))
    pb = (-bsz) % tb
    if pb:
        frontier = jnp.pad(frontier, ((0, pb), (0, 0), (0, 0)))
        cw1 = jnp.pad(cw1, ((0, pb), (0, 0), (0, 0)))
        cw2 = jnp.pad(cw2, ((0, pb), (0, 0), (0, 0)))
    bp = bsz + pb

    n_slots = len(idx)
    idx = np.asarray(idx)
    cw1_sl = jnp.transpose(cw1[:, idx, :], (2, 0, 1))
    cw2_sl = jnp.transpose(cw2[:, idx, :], (2, 0, 1))
    seeds = jnp.transpose(frontier, (2, 0, 1))        # [4, B, F]
    table_t = table_perm.T                            # [E, N]

    grid = (bp // tb, f_cnt)
    kernel = _make_subtree_kernel(tuple(sched), prf_method)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((4, tb, 1), lambda i, f: (0, i, f)),
            pl.BlockSpec((4, tb, n_slots), lambda i, f: (0, i, 0)),
            pl.BlockSpec((4, tb, n_slots), lambda i, f: (0, i, 0)),
            pl.BlockSpec((e, c), lambda i, f: (0, f)),
        ],
        out_specs=pl.BlockSpec((tb, e), lambda i, f: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, e), jnp.int32),
        interpret=interpret,
        # key tiles are independent; the subtree axis accumulates into
        # the same [tb, E] output block (reduction dim -> "arbitrary")
        compiler_params=_compiler_params(("parallel", "arbitrary")),
    )(seeds, cw1_sl, cw2_sl, table_t)
    return out[:bsz]


def _subtree_contract_pallas_impl(frontier, cw1, cw2, table_perm, *,
                                  depth: int, f_levels: int,
                                  interpret=False, tb: int | None = None,
                                  prf_method: int = 2):
    """Fused phase-2: expand every frontier subtree in VMEM and contract.

    frontier:   [B, F, 4] u32 — phase-1 output seeds (subtree f of key b).
    cw1, cw2:   [B, 64, 4] u32 — full codeword arrays (wire layout).
    table_perm: [N, E] int32 — bit-reverse-permuted table, N = F * C.
    prf_method: 2 = ChaCha20-12, 1 = Salsa20-12 (for AES see
    ``subtree_contract_pallas_aes``).
    Returns [B, E] int32 shares: sum_f leaves(f) . chunk(f).
    """
    levels = depth - f_levels
    # phase-2 codeword slots, kernel level k = global flat level
    # depth-1-(f_levels+k), branches adjacent (binary wire layout 2i+b)
    idx = [2 * (depth - 1 - (f_levels + k)) + b
           for k in range(levels) for b in (0, 1)]
    return _subtree_contract_run(
        frontier, cw1, cw2, table_perm, idx=idx, sched=(2,) * levels,
        prf_method=prf_method, interpret=interpret, tb=tb)


_subtree_contract_pallas_jit = functools.partial(jax.jit, static_argnames=(
    "depth", "f_levels", "interpret", "tb", "prf_method"))(
        _subtree_contract_pallas_impl)


def subtree_contract_pallas(frontier, cw1, cw2, table_perm, *,
                            depth: int, f_levels: int,
                            interpret=False, tb: int | None = None,
                            prf_method: int = 2):
    """Jit-wrapped fused subtree kernel; ``interpret=True`` runs EAGERLY
    (see ``chacha_level_step_pallas`` — interpret-under-jit compile
    blows up super-linearly on XLA-CPU)."""
    fn = (_subtree_contract_pallas_impl if interpret
          else _subtree_contract_pallas_jit)
    return fn(frontier, cw1, cw2, table_perm, depth=depth,
              f_levels=f_levels, interpret=interpret, tb=tb,
              prf_method=prf_method)


def _subtree_contract_pallas_mixed_impl(frontier, cw1, cw2, table_perm, *,
                                        ars: tuple, f_lv: int,
                                        interpret=False,
                                        tb: int | None = None,
                                        prf_method: int = 2):
    """Mixed-radix (radix-4) variant: phase-2 covers eval levels
    ``ars[f_lv:]`` with the mixed codeword layout (``radix4.cw_offsets``,
    level-major slots).  Same VMEM-resident expand+contract as the binary
    kernel; the wider fan-out means half the levels per subtree."""
    from ..core.radix4 import cw_offsets

    offs = cw_offsets(ars)
    sched = tuple(ars[f_lv:])
    idx = [offs[j] + b for j in range(f_lv, len(ars))
           for b in range(ars[j])]
    return _subtree_contract_run(
        frontier, cw1, cw2, table_perm, idx=idx, sched=sched,
        prf_method=prf_method, interpret=interpret, tb=tb)


_subtree_contract_pallas_mixed_jit = functools.partial(
    jax.jit, static_argnames=("ars", "f_lv", "interpret", "tb",
                              "prf_method"))(
        _subtree_contract_pallas_mixed_impl)


def subtree_contract_pallas_mixed(frontier, cw1, cw2, table_perm, *,
                                  ars: tuple, f_lv: int,
                                  interpret=False, tb: int | None = None,
                                  prf_method: int = 2):
    """Jit-wrapped mixed-radix subtree kernel; ``interpret=True`` runs
    EAGERLY (see ``chacha_level_step_pallas``)."""
    fn = (_subtree_contract_pallas_mixed_impl if interpret
          else _subtree_contract_pallas_mixed_jit)
    return fn(frontier, cw1, cw2, table_perm, ars=ars, f_lv=f_lv,
              interpret=interpret, tb=tb, prf_method=prf_method)


def pallas_chunk_leaves(n: int) -> int:
    """Leaves per subtree for the Pallas path.  Unlike the XLA path's
    ``choose_chunk`` (which scales with batch), the bound here is the
    per-key-tile VMEM cipher state, fixed by (PALLAS_TB, PALLAS_MAX_C)."""
    c = 1
    while c * 2 <= min(n, PALLAS_MAX_C):
        c *= 2
    return c
