"""Codesign join: batch-PIR accuracy sweeps x measured DPF kernel perf.

Counterpart of the reference's
``paper/experimental/codesign/join_batch_pir_accuracy_with_gpu_dpf.py:49-133``:
combines (a) recovery/accuracy summaries from a config sweep with (b)
measured TPU eval throughput to produce latency/throughput-vs-accuracy
frontier points, modeling hot+cold service on two devices (or one).
"""

from __future__ import annotations


def join_sweep_with_perf(sweep_results, perf_results, entry_size_bytes=64):
    """Join sweep summaries with measured perf dicts.

    perf_results: list of dicts from ``dpf_tpu.utils.bench.test_dpf_perf``
    (keys: entries, dpfs_per_sec, ...).  For each sweep config, the hot and
    cold tables are matched to the smallest benchmarked table size that
    covers their bin count, and per-query latency/throughput is derived.

    Returns a list of frontier points:
      {accuracy, mean_recovered, queries_per_sec, latency_ms, upload_bytes,
       download_bytes, config}
    """
    perf_by_entries = sorted(
        ((int(p["entries"]), float(p["dpfs_per_sec"])) for p in perf_results))
    if not perf_by_entries:
        raise ValueError("no perf results to join against")

    def dpfs_per_sec_for(table_len):
        """(rate, extrapolated?) — the smallest measured size covering
        ``table_len``, or a 1/N extrapolation past the largest measured
        point (flagged so frontier consumers can see which points rest
        on real measurements)."""
        for entries, rate in perf_by_entries:
            if entries >= max(table_len, 1):
                return rate, False
        entries, rate = perf_by_entries[-1]
        return rate * entries / max(table_len, 1), True

    points = []
    for s in sweep_results:
        cfg = s.get("config", {})
        extra = s["extra"]
        qh = s["pir_config"]["queries_to_hot"]
        qc = s["pir_config"]["queries_to_cold"]
        # one DPF per bin per query round; each bin is its own mini-table
        hot_bins = max(1, extra["hot_table_size"]
                       // max(extra["hot_table_entries_per_bin"], 1))
        cold_bins = (extra["cold_table_size"]
                     // max(extra["cold_table_entries_per_bin"], 1)
                     if extra["cold_table_size"] else 0)
        hot_rate, hot_ex = dpfs_per_sec_for(
            extra["hot_table_entries_per_bin"])
        cold_rate, cold_ex = (
            dpfs_per_sec_for(extra["cold_table_entries_per_bin"])
            if cold_bins else (float("inf"), False))
        # hot and cold tables served by two devices in parallel (ref :49-133)
        hot_time = qh * hot_bins / hot_rate
        cold_time = (qc * cold_bins / cold_rate) if cold_bins else 0.0
        service_time = max(hot_time, cold_time)
        points.append({
            "config": cfg,
            "accuracy": (s.get("accuracy_stats") or {}).get("roc_auc"),
            "mean_recovered": s["mean_recovered"],
            "latency_ms": service_time * 1e3,
            "queries_per_sec": (1.0 / service_time if service_time > 0
                                else float("inf")),
            "upload_bytes": s["cost"]["upload_communication"],
            "download_bytes": s["cost"]["download_communication"],
            "perf_extrapolated": bool(hot_ex or cold_ex),
        })
    points.sort(key=lambda p: p["mean_recovered"], reverse=True)
    return points


def pareto_frontier(points, x="latency_ms", y="mean_recovered"):
    """Lower-x / higher-y pareto-optimal subset."""
    frontier = []
    best_y = -float("inf")
    for p in sorted(points, key=lambda p: (p[x], -p[y])):
        if p[y] > best_y:
            frontier.append(p)
            best_y = p[y]
    return frontier
