"""Figure rendering for sweep / codesign results (role of the reference's
``sweep/{taobao,movielens,language_model}_plot.py`` and
``codesign/plot_{rec,lm}.py``).  Matplotlib is optional; functions raise a
clear error if it is missing."""

from __future__ import annotations


def _plt():
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("matplotlib is required for plotting") from e


def plot_recovery_vs_queries(sweep_results, out_path: str):
    """Mean fraction recovered vs hot-query budget, one line per bin size."""
    plt = _plt()
    by_bin = {}
    for r in sweep_results:
        cfg = r["config"]
        by_bin.setdefault(cfg["bin_fraction"], []).append(
            (cfg["queries_to_hot"], r["mean_recovered"]))
    fig, ax = plt.subplots(figsize=(6, 4))
    for bin_fraction, pts in sorted(by_bin.items()):
        pts.sort()
        ax.plot([p[0] for p in pts], [p[1] for p in pts], marker="o",
                label="bin_fraction=%g" % bin_fraction)
    ax.set_xlabel("queries to hot table")
    ax.set_ylabel("mean fraction of batch recovered")
    ax.set_ylim(0, 1.05)
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_latency_vs_recovery(points, out_path: str, frontier=None):
    """Codesign frontier: per-batch service latency vs recovery (accuracy)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.scatter([p["latency_ms"] for p in points],
               [p["mean_recovered"] for p in points],
               s=18, alpha=0.6, label="configs")
    if frontier:
        fr = sorted(frontier, key=lambda p: p["latency_ms"])
        ax.plot([p["latency_ms"] for p in fr],
                [p["mean_recovered"] for p in fr],
                color="crimson", marker="o", label="pareto frontier")
    ax.set_xlabel("service latency (ms)")
    ax.set_ylabel("mean fraction recovered")
    ax.set_xscale("log")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_throughput_table(perf_results, out_path: str):
    """dpfs/sec vs table size, one line per PRF (the README-style table)."""
    plt = _plt()
    by_prf = {}
    for r in perf_results:
        by_prf.setdefault(r.get("prf", "?"), []).append(
            (r["entries"], r["dpfs_per_sec"]))
    fig, ax = plt.subplots(figsize=(6, 4))
    for prf_name, pts in sorted(by_prf.items()):
        pts.sort()
        ax.plot([p[0] for p in pts], [p[1] for p in pts], marker="s",
                label=prf_name)
    ax.set_xlabel("table entries")
    ax.set_ylabel("dpfs / sec")
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.legend()
    ax.grid(True, alpha=0.3, which="both")
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path
