"""Batch-PIR scheduling optimizer: hot/cold split, co-location, binning.

Capability port of the reference's batch-PIR layer
(``paper/experimental/batch_pir/batch_pir_optimization.py:24-267``): given
train/validation *access patterns* (lists of index sets, e.g. the embedding
rows a user's inference touches), plan private batched lookups that maximize
the fraction of needed entries recovered under a budget of DPF queries:

* **hot/cold split** — the most frequently accessed ``cache_fraction`` of
  entries form a small "hot" table served with cheaper queries (ref ``:66-83``).
* **binning** — each table is cut into bins; one DPF query retrieves exactly
  one entry per bin, so a batch of needed indices spread over many bins is
  served by few queries (ref ``:49-64``).
* **co-location** — entries frequently co-accessed with x are stored in x's
  row, so recovering x recovers them for free (ref ``:198-248``).
* **cost model** — ``DPFCost(computation, upload, download)`` with the same
  2-KB/log2(n) key-size accounting (ref ``:85-88,187-194``).

Beyond the reference (which only *models* the protocol), ``PrivateLookupClient``
/ ``PrivateLookupServer`` execute the planned queries for real through the
TPU DPF backend.
"""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict
from dataclasses import asdict, dataclass, field

import numpy as np

from ..obs.tracer import span


# the one scheme/radix membership rule, shared with the DPF ctor
from ..utils.config import check_construction as _check_construction_args


@dataclass(frozen=True)
class HotColdConfig:
    cache_size_fraction: float = 1.0


@dataclass(frozen=True)
class CollocateConfig:
    num_collocate: int = 0


@dataclass(frozen=True)
class PIRConfig:
    bin_fraction: float = 0.1      # fraction of a table forming one bin
    entry_size_bytes: int = 256
    queries_to_hot: int = 1
    queries_to_cold: int = 0
    # construction the cost model prices upload bytes for: "logn"
    # (binary GGM or, with radix=4, the mixed-radix tree — both ship the
    # same fixed wire container) or "sqrtn" (O(sqrt N) keys).  NOT
    # "auto": the planner prices a concrete construction — resolve the
    # per-group winner first (PrivateLookupServer.group_constructions)
    scheme: str = "logn"
    radix: int = 2

    def __post_init__(self):
        # the cost model prices a CONCRETE construction: same membership
        # rule as the serving stack, minus "auto"
        _check_construction_args(self.scheme, self.radix,
                                 schemes=("logn", "sqrtn"))


@dataclass
class DPFCost:
    computation: int = 0
    upload_communication: int = 0
    download_communication: int = 0

    def _asdict(self):
        return asdict(self)


def dpf_key_cost_bytes(table_size: int, scheme: str = "logn",
                       radix: int = 2) -> int:
    """Upload bytes per query: the EXACT wire size of one serialized key
    for the construction, over the padded power-of-two bin domain the
    lookup servers actually use (``_pad_pow2``, 128-entry floor).

    The pre-PR model used the reference's analytic ``16 B x 4 x
    log2(n)`` accounting (ref ``:85-88``) — but real binary-GGM and
    radix-4 keys ship in the fixed 524-int32 container (2096 B
    regardless of n), and sqrt-N keys are ``(4 + K + 2R) x 16`` B.
    Fuzz-checked against ``serialize(...)`` of real keys in
    tests/test_batch_pir.py, so the planner's upload numbers match what
    the client transmits byte for byte.
    """
    if table_size < 1:
        return 0
    # table_size == 1 still prices a full key: the lookup servers pad
    # every bin to the 128-entry floor and the client transmits a real
    # key over that padded domain (the pre-PR analytic model priced
    # log2(1) = 0 bytes, undercounting single-entry bins by a whole key)
    # a CONCRETE construction only — resolve "auto" per group first
    # (PrivateLookupServer.group_constructions)
    _check_construction_args(scheme, radix, schemes=("logn", "sqrtn"))
    n = _pad_pow2(table_size)
    if scheme == "sqrtn":
        from ..core.sqrtn import default_split
        k, r = default_split(n)
        return (4 + k + 2 * r) * 16
    from ..core.keygen import KEY_WORDS
    return KEY_WORDS * 4  # both logn radices fill the same container


class BatchPIROptimize:
    """Plan (and cost) private batched lookups over access patterns."""

    def __init__(self, train_set, validation_set,
                 hotcold_config: HotColdConfig = HotColdConfig(),
                 collocate_config: CollocateConfig = CollocateConfig(),
                 pir_config: PIRConfig = PIRConfig(),
                 collocate_cache: str | dict | None = None):
        self.hotcold_config = hotcold_config
        self.collocate_config = collocate_config
        self.pir_config = pir_config
        self.train = [list(s) for s in train_set]
        self.val = [list(s) for s in validation_set]

        self._count_accesses()
        self._split_hot_cold()
        self._build_collocation(collocate_cache)
        self._build_bins()
        self.accuracy_stats = None
        self.cost = DPFCost()

    # -------------------------------------------------------- statistics

    def _count_accesses(self):
        self.embedding_counts = Counter()
        for idx_set in self.train:
            self.embedding_counts.update(idx_set)
        self.all_embedding_indices = set(self.embedding_counts)
        for idx_set in self.val:
            self.all_embedding_indices.update(idx_set)
        self.num_embeddings = len(self.all_embedding_indices)

    def _split_hot_cold(self):
        frac = self.hotcold_config.cache_size_fraction
        n_hot = int(frac * self.num_embeddings)
        by_freq = sorted(self.all_embedding_indices,
                         key=lambda x: self.embedding_counts[x], reverse=True)
        self.hot_table = by_freq[:n_hot]
        self.cold_table = by_freq[n_hot:]
        # shuffle within each table so bins are unbiased — must be stable
        # ACROSS PROCESSES (client and server derive bins independently),
        # so use a keyed digest, not the per-process-salted builtin hash()
        def stable_key(x):
            import hashlib
            return hashlib.sha256(str(x).encode()).digest()
        self.hot_table.sort(key=stable_key)
        self.cold_table.sort(key=stable_key)

    def _build_collocation(self, cache):
        """Top co-accessed neighbors per entry (cacheable: it is O(sum k^2))."""
        k = self.collocate_config.num_collocate
        if isinstance(cache, str) and os.path.exists(cache):
            with open(cache) as f:
                loaded = json.load(f)
            self.collocation_map = {int(i): v for i, v in loaded.items()}
            return
        if isinstance(cache, dict):
            self.collocation_map = {int(i): v for i, v in cache.items()}
            return
        co = defaultdict(Counter)
        if k > 0:
            for idx_set in self.train:
                uniq = set(idx_set)
                for src in uniq:
                    for dst in uniq:
                        if src != dst:
                            co[src][dst] += 1
        self.collocation_map = {
            idx: [d for d, _ in co[idx].most_common(k)] if idx in co else []
            for idx in self.all_embedding_indices}
        if isinstance(cache, str):
            with open(cache, "w") as f:
                json.dump(self.collocation_map, f)

    def _build_bins(self):
        def bins_of(table):
            if not table:
                return [], 0
            per_bin = max(1, int(len(table) * self.pir_config.bin_fraction))
            return ([set(table[i:i + per_bin])
                     for i in range(0, len(table), per_bin)], per_bin)

        self.hot_table_bins, self.hot_entries_per_bin = bins_of(self.hot_table)
        self.cold_table_bins, self.cold_entries_per_bin = \
            bins_of(self.cold_table)

    # -------------------------------------------------------------- fetch

    def fetch(self, batch_indices):
        """Greedy multi-query plan for one batch of needed indices.

        Returns (recovered index set, DPFCost).  Each query round retrieves
        at most one entry per bin; the most-needed unrecovered candidate in
        each bin wins (ref ``:144-196``).
        """
        counts = Counter(batch_indices)
        targets = set(counts)
        recovered = set()

        def one_query(bins):
            for b in bins:
                cands = b & targets
                if not cands:
                    continue
                best = max(cands, key=lambda x: (-1, 0) if x in recovered
                           else (0, counts[x]))
                if best not in recovered:
                    recovered.add(best)

        for _ in range(self.pir_config.queries_to_hot):
            one_query(self.hot_table_bins)
        for _ in range(self.pir_config.queries_to_cold):
            one_query(self.cold_table_bins)

        collocated = set()
        for idx in recovered:
            collocated.update(self.collocation_map.get(idx, []))
        all_recovered = recovered | collocated

        qh, qc = (self.pir_config.queries_to_hot,
                  self.pir_config.queries_to_cold)
        sch, rad = self.pir_config.scheme, self.pir_config.radix
        cost = DPFCost(
            computation=qh * len(self.hot_table) + qc * len(self.cold_table),
            upload_communication=(
                qh * dpf_key_cost_bytes(self.hot_entries_per_bin, sch, rad)
                * len(self.hot_table_bins)
                + qc * dpf_key_cost_bytes(self.cold_entries_per_bin, sch, rad)
                * len(self.cold_table_bins)),
            download_communication=(
                (qh * len(self.hot_table_bins)
                 + qc * len(self.cold_table_bins))
                * self.pir_config.entry_size_bytes))
        return all_recovered, cost

    # ---------------------------------------------------------- evaluate

    def evaluate(self, limit=None):
        """Fraction-of-batch-recovered over the validation access patterns."""
        self.percentage_of_query_recovered = []
        for val in self.val[:limit]:
            if not val:
                continue
            recovered, self.cost = self.fetch(val)
            hit = set(x for x in recovered if x in val)
            self.percentage_of_query_recovered.append(
                len(hit) / len(set(val)))
        return self.percentage_of_query_recovered

    def evaluate_with_model(self, dataset_module, limit=None):
        """Evaluate + downstream model accuracy with unrecovered embeddings
        masked (the accuracy-vs-PIR-budget experiment, ref ``:114-118``)."""
        self.evaluate(limit=limit)
        self.accuracy_stats = dataset_module.evaluate(self)
        return self.accuracy_stats

    def summarize_evaluation(self):
        p = self.percentage_of_query_recovered
        summary = {
            "pir_config": asdict(self.pir_config),
            "hotcold_config": asdict(self.hotcold_config),
            "collocate_config": asdict(self.collocate_config),
            "mean_recovered": float(np.mean(p)),
            **{"recovered_p_%d" % q: float(np.percentile(p, q))
               for q in (0, 5, 10, 50, 90, 95)},
            "cost": self.cost._asdict(),
            "accuracy_stats": self.accuracy_stats,
            "extra": {
                "hot_table_size": len(self.hot_table),
                "cold_table_size": len(self.cold_table),
                "hot_table_entries_per_bin": self.hot_entries_per_bin,
                "cold_table_entries_per_bin": self.cold_entries_per_bin,
            },
        }
        return summary


# ---------------------------------------------------------------------------
# Real execution of a batch-PIR plan through the TPU DPF backend.
# (The reference models the protocol analytically; this runs it.)
# ---------------------------------------------------------------------------

def _pad_pow2(n, lo=128):
    from ..core.u128 import next_pow2
    return next_pow2(max(n, lo))


def _resolve_construction(scheme: str, radix: int, n: int, group_size: int,
                          entry_size: int, prf_method: int):
    """The concrete construction of one (n, G) batch-PIR size group.

    ``scheme="auto"`` asks the scheme-level tuning cache
    (``tune.lookup_scheme`` — the winner ``benchmark.py
    --autotune-scheme`` measured for this shape on this machine) and
    falls back to the caller's explicit ``(logn, radix)`` construction
    on a cold cache.  Client and server derive this independently, so it
    must be deterministic given the same bins and tuning-cache state —
    the same cross-process contract as the stable bin shuffle.
    """
    if scheme == "sqrtn":
        return "sqrtn", 2
    if scheme == "auto":
        from ..core.u128 import next_pow2
        from ..tune.cache import lookup_scheme
        rec = lookup_scheme(n=n, entry_size=entry_size,
                            batch=next_pow2(max(1, group_size)),
                            prf_method=prf_method)
        if rec and rec.get("scheme") in ("logn", "sqrtn"):
            return rec["scheme"], int(rec.get("radix") or 2)
    return "logn", radix


@dataclass
class _SizeGroup:
    """All bins sharing one padded mini-table size n, stacked."""
    idxs: list           # bin indices, in stacked (axis 0) order
    tables: object       # [G + gpad, n, E] device array, permuted per scheme
    gpad: int            # zero-bin pad rows appended for the mesh
    scheme: str          # resolved construction for this (n, G) group
    radix: int


class PrivateLookupServer:
    """Holds one bin-structured table; answers DPF queries per bin.

    Each bin is padded to a power-of-two mini-table; bins of equal padded
    size form one (n, G) *size group* stacked into a [G, n, E] device
    array, so one batched per-key-table evaluation
    (``expand.expand_and_contract_per_key_tables`` and its radix-4 /
    sqrt-N counterparts) answers one query round across all of them in a
    single device dispatch — the reference's layer loops bins on the
    host instead.  ``answer`` is the production path: packed wire-codec
    ingest, tuning-cache knob resolution per group, and ALL groups
    dispatched asynchronously before one blocking gather;
    ``answer_scalar`` keeps the per-key scalar path as the parity
    oracle.  ``stream()`` serves multi-round query streams through one
    ``ServingEngine`` per size group.
    """

    def __init__(self, table: np.ndarray, bins, prf=None, radix: int = 2,
                 mesh=None, scheme: str = "logn"):
        """mesh: optional ``jax.sharding.Mesh`` — equal-size bin groups
        are embarrassingly parallel, so the stacked [G, n, E] tables and
        the per-bin key batch shard over ALL mesh axes flattened onto
        the group axis (G padded with zero bins to the device count);
        one query round then runs as one SPMD dispatch across the mesh.
        The reference has no multi-device batch-PIR at all.

        scheme: "logn" (binary GGM, or the radix-4 tree with radix=4),
        "sqrtn" (O(sqrt N) keys, flat PRF grid), or "auto" — each
        (n, G) size group resolves its construction from the scheme
        tuning cache via ``_resolve_construction`` (cold cache: the
        explicit logn/radix construction).  The client must be built
        with the same scheme/radix arguments so both sides derive the
        same per-group construction."""
        from ..api import DPF
        from ..core import expand, radix4
        _check_construction_args(scheme, radix)
        self.prf_method = DPF.DEFAULT_PRF if prf is None else prf
        self.radix = radix
        self.scheme = scheme
        self.mesh = mesh
        self.entry_size = table.shape[1]
        self.bins = [sorted(b) for b in bins]
        self.bin_sizes = []
        padded_tables = []
        for b in self.bins:
            sub = table[b] if b else np.zeros((1, self.entry_size), np.int32)
            n = _pad_pow2(len(sub))
            padded = np.zeros((n, self.entry_size), np.int32)
            padded[:len(sub)] = sub
            padded_tables.append(padded)
            self.bin_sizes.append(n)

        def permute(padded, sch, rad):
            if sch == "sqrtn":  # the sqrt-N grid emits natural order
                return padded
            if rad == 4:
                perm = radix4.mixed_reverse_indices(
                    radix4.arities(padded.shape[0]))
                return np.ascontiguousarray(padded[perm])
            return expand.permute_table(padded)

        # group bins by padded size -> one stacked [G, n, E] device array
        # each; with a mesh, G pads to the device count and shards
        import jax.numpy as jnp
        by_size = {}  # n -> (bin indices, natural padded tables)
        for bi, (n, padded) in enumerate(zip(self.bin_sizes, padded_tables)):
            by_size.setdefault(n, ([], []))
            by_size[n][0].append(bi)
            by_size[n][1].append(padded)
        self._groups = {}
        self._tuned = {}  # (n, batch, scheme, radix) -> tuning-cache knobs
        for n, (idxs, tbls) in by_size.items():
            sch, rad = _resolve_construction(
                scheme, radix, n, len(idxs), self.entry_size,
                self.prf_method)
            stacked = np.stack([permute(t, sch, rad) for t in tbls])
            pad = 0
            if mesh is not None:
                pad = (-stacked.shape[0]) % mesh.size
                if pad:
                    stacked = np.concatenate(
                        [stacked, np.zeros((pad,) + stacked.shape[1:],
                                           np.int32)])
                stacked = self._shard(jnp.asarray(stacked))
            else:
                stacked = jnp.asarray(stacked)
            self._groups[n] = _SizeGroup(idxs, stacked, pad, sch, rad)

    def group_constructions(self) -> dict:
        """{bin size n: (scheme, radix)} — what each size group resolved
        to (diagnostics; with scheme="auto" this is the cache answer)."""
        return {n: (g.scheme, g.radix) for n, g in self._groups.items()}

    def _shard(self, arr):
        """Shard axis 0 (the bin-group axis) over every mesh axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(tuple(self.mesh.axis_names),
                 *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _pad_keys(self, packed, pad):
        """Pad the packed key batch (axis 0) to the sharded group size by
        repeating the last key (answers land in zero-table rows that the
        caller slices away) and co-shard with the tables."""
        import jax.numpy as jnp
        out = []
        for a in packed:
            a = np.asarray(a)
            if pad:
                a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
            out.append(self._shard(jnp.asarray(a))
                       if self.mesh is not None else jnp.asarray(a))
        return out

    # ------------------------------------------------------ the hot path

    def _group_knobs(self, n: int, batch: int, sch: str, rad: int) -> dict:
        """Program knobs for one (n, G) dispatch, tuning-cache first.

        The per-shape tuned entries (``tune.cache.lookup_eval_knobs``,
        populated by ``benchmark.py --autotune``/``--autotune-scheme``,
        nearest-batch fallback included) replace the pre-PR frozen
        heuristics; fields the cache cannot answer fall back to the
        same static choices (``expand.choose_chunk`` et al.).  The cache
        lookup is memoized per (n, batch, construction); the
        process-global fallbacks are re-read every call so
        ``set_dot_impl``/``apply_globals`` stay live, matching
        ``DPF.resolved_eval_knobs``."""
        from ..core import expand
        from ..core import prf as _prf
        from ..ops import matmul128
        key = (n, batch, sch, rad)
        tuned = self._tuned.get(key)
        if tuned is None:
            from ..tune.cache import lookup_eval_knobs, lookup_mesh_knobs
            tuned = lookup_eval_knobs(
                n=n, entry_size=self.entry_size, batch=batch,
                prf_method=self.prf_method, scheme=sch, radix=rad) or {}
            if not tuned and self.mesh is not None:
                # mesh-tagged fallback (benchmark.py --multichip
                # populates it).  The single-device entry stays
                # preferred: this group program evaluates FULL-range
                # per-key tables with the bins sharded over the mesh,
                # so its chunk range matches the single-device program
                # family — a mesh entry's chunks were searched over a
                # table-sharded program's PER-SHARD range and only
                # approximate it; still measured knobs for this device,
                # so better than frozen heuristics on a mesh-only-tuned
                # machine (values re-clamped against the bin range
                # below / at dispatch either way)
                from ..tune.fingerprint import mesh_tag
                tuned = lookup_mesh_knobs(
                    n=n, entry_size=self.entry_size, batch=batch,
                    prf_method=self.prf_method, scheme=sch, radix=rad,
                    mesh=mesh_tag(self.mesh)) or {}
            self._tuned[key] = tuned
        if sch == "sqrtn":
            rc = tuned.get("row_chunk")
            if tuned.get("kernel_impl", "xla") != "xla":
                # a tuned row_chunk rides only with ITS kernel (the
                # logn chunk_leaves rule below): the per-key-tables
                # program is always the fused xla scan, so a grid-
                # kernel winner's VMEM-capped chunk must not be pinned
                # onto it — fall back to the scan's own heuristic
                rc = None
            return {"dot_impl": tuned.get("dot_impl")
                    or matmul128.default_impl(),
                    # clamped against the decoded batch's split at
                    # dispatch (sqrtn.clamp_row_chunk)
                    "row_chunk": rc}
        chunk = tuned.get("chunk_leaves")
        if tuned.get("kernel_impl", "xla") != "xla":
            # a tuned chunk rides only with ITS kernel; the
            # per-key-tables program is always the fused xla one
            chunk = None
        return {"chunk_leaves": expand.clamp_chunk(chunk, n, batch),
                "dot_impl": tuned.get("dot_impl")
                or matmul128.default_impl(),
                "aes_impl": tuned.get("aes_impl") or _prf._aes_pair_impl(),
                "round_unroll": tuned.get("round_unroll",
                                          _prf.ROUND_UNROLL)}

    def _decode_group(self, n: int, grp: _SizeGroup, keys):
        """Packed-codec ingest for one size group's key list, with
        fail-fast validation: a wrong-domain or wrong-construction key
        is reported with its BIN index before any batch decode work (the
        pre-PR loop deserialized the whole group first)."""
        from ..core import keygen, radix4, sqrtn
        if len(keys) != len(grp.idxs):
            raise ValueError("size-%d group: expected %d keys, got %d"
                             % (n, len(grp.idxs), len(keys)))
        if grp.scheme == "sqrtn":
            try:
                arr = sqrtn.stack_sqrt_wire_keys(keys)
                kn = sqrtn.sqrt_wire_ns(arr)
            except ValueError as exc:
                raise ValueError("size-%d group (bins %s): %s"
                                 % (n, grp.idxs, exc)) from None
            bad = np.flatnonzero(kn != n)
            if bad.size:
                raise ValueError(
                    "key for bin %d (bin size %d) got n=%d"
                    % (grp.idxs[bad[0]], n, kn[bad[0]]))
            return sqrtn.decode_sqrt_keys_batched(arr)
        try:
            arr = keygen.stack_wire_keys(keys)
        except ValueError as exc:
            raise ValueError("size-%d group (bins %s): %s"
                             % (n, grp.idxs, exc)) from None
        marker, kn = keygen.wire_headers(arr)
        bad = np.flatnonzero(marker != (4 if grp.radix == 4 else 0))
        if bad.size:
            raise ValueError(
                "key for bin %d (bin size %d) is not a %s key "
                "(radix marker %d)"
                % (grp.idxs[bad[0]], n,
                   "radix-4" if grp.radix == 4 else "binary",
                   marker[bad[0]]))
        bad = np.flatnonzero(kn != n)
        if bad.size:
            raise ValueError("key for bin %d (bin size %d) got n=%d"
                             % (grp.idxs[bad[0]], n, kn[bad[0]]))
        decode = (radix4.decode_mixed_keys_batched if grp.radix == 4
                  else keygen.decode_keys_batched)
        return decode(arr)

    def _run_group_program(self, n: int, grp: _SizeGroup, pk, tables=None):
        """Dispatch one packed key batch against the group's stacked
        tables (``tables`` overrides for the streaming pad) and return
        the device array WITHOUT forcing a host sync — JAX async
        dispatch lets the caller enqueue every group before blocking."""
        from ..core import expand, radix4, sqrtn
        tables = grp.tables if tables is None else tables
        knobs = self._group_knobs(n, pk.batch, grp.scheme, grp.radix)
        if grp.scheme == "sqrtn":
            seeds, cw1, cw2 = self._pad_keys(
                (pk.seeds, pk.cw1, pk.cw2), 0)
            rc = sqrtn.clamp_row_chunk(knobs["row_chunk"], pk.n_codewords,
                                       pk.n_keys, pk.batch)
            return sqrtn.eval_contract_per_key_tables(
                seeds, cw1, cw2, tables, prf_method=self.prf_method,
                dot_impl=knobs["dot_impl"], row_chunk=rc)
        cw1, cw2, last = self._pad_keys((pk.cw1, pk.cw2, pk.last), 0)
        if grp.radix == 4:
            return radix4.expand_and_contract_per_key_tables_mixed(
                cw1, cw2, last, tables, n=n, prf_method=self.prf_method,
                **knobs)
        return expand.expand_and_contract_per_key_tables(
            cw1, cw2, last, tables, depth=n.bit_length() - 1,
            prf_method=self.prf_method, **knobs)

    def answer(self, keys_per_bin):
        """keys_per_bin: one serialized key per bin -> [n_bins, E] shares.

        The production path: per size group the whole key batch decodes
        through the packed wire codec (``_decode_group``), knobs resolve
        from the tuning cache (``_group_knobs``), and every group's
        jitted program is dispatched asynchronously — one blocking
        gather at the end instead of the pre-PR host round-trip per
        group.  Bit-identical to ``answer_scalar``."""
        if len(keys_per_bin) != len(self.bins):
            raise ValueError("expected one key per bin (%d bins), got %d"
                             % (len(self.bins), len(keys_per_bin)))
        pending = []
        for n, grp in self._groups.items():
            pk = self._decode_group(n, grp,
                                    [keys_per_bin[bi] for bi in grp.idxs])
            pk = pk.pad_to(len(grp.idxs) + grp.gpad)
            pending.append((grp, self._run_group_program(n, grp, pk)))
        out = np.zeros((len(self.bins), self.entry_size), np.int32)
        for grp, dev in pending:
            out[grp.idxs] = np.asarray(dev)[:len(grp.idxs)]
        return out

    def answer_scalar(self, keys_per_bin):
        """The pre-batched answer path, kept as the parity oracle (and
        the benchmark baseline): per-key scalar deserialize + pack,
        static heuristic knobs, one blocking host sync per size group.
        Same device kernels, so ``answer`` must match it bit for bit
        (asserted in tests and in ``serve/bench_pir.py`` before any
        timing)."""
        from ..core import expand, keygen, radix4, sqrtn
        from ..core import prf as _prf
        from ..ops import matmul128
        if len(keys_per_bin) != len(self.bins):
            raise ValueError("expected one key per bin (%d bins), got %d"
                             % (len(self.bins), len(keys_per_bin)))
        out = np.zeros((len(self.bins), self.entry_size), np.int32)
        for n, grp in self._groups.items():
            keys = [keys_per_bin[bi] for bi in grp.idxs]
            if grp.scheme == "sqrtn":
                sk = [sqrtn.deserialize_sqrt_key(k) for k in keys]
                for bi, k in zip(grp.idxs, sk):
                    if k.n != n:
                        raise ValueError(
                            "key for bin %d (bin size %d) got n=%d"
                            % (bi, n, k.n))
                seeds, cw1, cw2 = self._pad_keys(
                    sqrtn.pack_sqrt_keys(sk), grp.gpad)
                shares = sqrtn.eval_contract_per_key_tables(
                    seeds, cw1, cw2, grp.tables,
                    prf_method=self.prf_method,
                    dot_impl=matmul128.default_impl())
            elif grp.radix == 4:
                mk = [radix4.deserialize_mixed_key(k) for k in keys]
                for bi, k in zip(grp.idxs, mk):
                    if k.n != n:
                        raise ValueError(
                            "key for bin %d (bin size %d) got n=%d"
                            % (bi, n, k.n))
                cw1, cw2, last = self._pad_keys(
                    radix4.pack_mixed_keys(mk), grp.gpad)
                shares = radix4.expand_and_contract_per_key_tables_mixed(
                    cw1, cw2, last, grp.tables, n=n,
                    prf_method=self.prf_method,
                    chunk_leaves=expand.choose_chunk(n, len(mk)),
                    dot_impl=matmul128.default_impl(),
                    aes_impl=_prf._aes_pair_impl(),
                    round_unroll=_prf.ROUND_UNROLL)
            else:
                flat = [keygen.deserialize_key(k) for k in keys]
                for bi, fk in zip(grp.idxs, flat):
                    if fk.n != n:
                        raise ValueError(
                            "key for bin %d (bin size %d) got n=%d"
                            % (bi, n, fk.n))
                cw1, cw2, last = self._pad_keys(
                    expand.pack_keys(flat), grp.gpad)
                shares = expand.expand_and_contract_per_key_tables(
                    cw1, cw2, last, grp.tables, depth=n.bit_length() - 1,
                    prf_method=self.prf_method,
                    chunk_leaves=expand.choose_chunk(n, len(flat)),
                    dot_impl=matmul128.default_impl(),
                    aes_impl=_prf._aes_pair_impl(),
                    round_unroll=_prf.ROUND_UNROLL)
            out[grp.idxs] = np.asarray(shares)[:len(grp.idxs)]
        return out

    # ------------------------------------------------------- streaming

    def stream(self, *, max_in_flight: int = 2, warmup: bool = True,
               retry=None):
        """A ``LookupStream`` serving multi-round query batches through
        one ``ServingEngine`` per (n, G) size group — vectorized ingest,
        precompiled fixed shapes (shape buckets keyed on the group), and
        an in-flight dispatch window per group.  ``retry`` (a
        ``serve.RetryPolicy``) re-attempts failed group dispatches —
        see docs/BATCH_PIR.md and docs/SERVING.md "Fault tolerance".
        """
        return LookupStream(self, max_in_flight=max_in_flight,
                            warmup=warmup, retry=retry)


class _GroupStreamServer:
    """``ServingEngine`` adapter presenting one (n, G) size group as a
    standalone server: the engine only needs the
    ``_decode_batch``/``_dispatch_packed`` pair plus shape attributes.
    A group's batch is ALWAYS exactly its (mesh-padded) size — one key
    per bin — so the dispatch trims the engine's power-of-two bucket
    pad back off and runs the same exact-shape program as ``answer``
    (no pad rows evaluated; the single bucket exists to satisfy the
    engine's shape discipline and its warmup precompile)."""

    def __init__(self, owner: PrivateLookupServer, n: int,
                 grp: _SizeGroup):
        self._owner = owner
        self._grp = grp
        self._gtot = len(grp.idxs) + grp.gpad
        self.n = n                      # engine: depth for warmup keys
        self.entry_size = owner.entry_size
        self.batch_size = self._gtot    # engine: dispatch cap
        self.scheme = grp.scheme        # engine: sqrt-N warmup key shape

    def _decode_batch(self, keys):
        if hasattr(keys, "batch"):  # pre-decoded by LookupStream.submit
            return keys             # (all-groups-validate-first contract)
        return self._owner._decode_group(self.n, self._grp, keys)

    def _dispatch_packed(self, pk):
        pk = (pk.slice(0, self._gtot) if pk.batch > self._gtot
              else pk.pad_to(self._gtot))
        return self._owner._run_group_program(self.n, self._grp, pk)


class LookupRoundFuture:
    """One submitted query round; ``result()`` assembles the
    [n_bins, E] share matrix from the per-group engine futures (blocking
    only on this round's dispatches, FIFO per group)."""

    __slots__ = ("_n_bins", "_entry_size", "_parts", "_value")

    def __init__(self, n_bins, entry_size, parts):
        self._n_bins = n_bins
        self._entry_size = entry_size
        self._parts = parts             # [(group, EngineFuture)]
        self._value = None

    def done(self) -> bool:
        """True once this round has been RESOLVED — its result
        materialized by ``result()`` or a covering ``drain()``.  The
        engines are threadless (EngineFuture contract): nothing flips
        this in the background, so call ``result()`` to block rather
        than polling."""
        return (self._value is not None
                or all(f.done() for _, f in self._parts))

    def result(self) -> np.ndarray:
        if self._value is None:
            out = np.zeros((self._n_bins, self._entry_size), np.int32)
            for grp, fut in self._parts:
                out[grp.idxs] = fut.result()
            self._value = out
            self._parts = []
        return self._value


class LookupStream:
    """Streaming batch-PIR serving: multi-round query batches pipelined
    through one ``ServingEngine`` per (n, G) size group.

    Each engine owns a single shape bucket (the group's padded
    power-of-two size), so ingest is the packed group codec, the
    program shape is fixed and precompiled at warmup, and up to
    ``max_in_flight`` rounds per group overlap host decode with device
    execution (on a synchronous backend the win is the ingest + shape
    reuse).  ``submit`` returns a ``LookupRoundFuture`` immediately;
    results are bit-identical to ``PrivateLookupServer.answer``.

    ``retry`` (a ``serve.RetryPolicy``) re-attempts a failed group
    dispatch under bounded backoff — ``ServingEngine.submit``'s
    partial-unwind keeps the engine consistent between attempts, and
    re-attempts count into that engine's ``stats.retries`` (visible in
    ``counters()``).  ``LoadShed``/deadline still propagate
    immediately (admission decisions are never retried).
    """

    def __init__(self, server: PrivateLookupServer, *,
                 max_in_flight: int = 2, warmup: bool = True,
                 retry=None):
        from ..core.u128 import next_pow2
        from ..serve import ServingEngine
        self._server = server
        self._n_bins = len(server.bins)
        self._retry = retry
        self._engines = []              # [(n, group, engine)]
        for n, grp in server._groups.items():
            bucket = next_pow2(len(grp.idxs) + grp.gpad)
            adapter = _GroupStreamServer(server, n, grp)
            self._engines.append((n, grp, ServingEngine(
                adapter, max_in_flight=max_in_flight, buckets=[bucket],
                warmup=warmup, label="n%dxG%d" % (n, len(grp.idxs)))))

    def submit(self, keys_per_bin) -> LookupRoundFuture:
        """Decode + dispatch one query round (one key per bin); returns
        a future immediately.  Backpressure applies per group engine.

        EVERY group decodes (and fail-fast validates) before ANY engine
        dispatch: a bad key in a later group must not leave earlier
        groups' dispatches orphaned in their in-flight windows (or skew
        their counters) — the engines then receive the pre-decoded
        packed batches."""
        if len(keys_per_bin) != self._n_bins:
            raise ValueError("expected one key per bin (%d bins), got %d"
                             % (self._n_bins, len(keys_per_bin)))
        with span("round", bins=self._n_bins,
                  groups=len(self._engines)):
            with span("pack", phase="group_decode"):
                decoded = [
                    (grp, eng, self._server._decode_group(
                        n, grp, [keys_per_bin[bi] for bi in grp.idxs]))
                    for n, grp, eng in self._engines]
            if self._retry is None:
                parts = [(grp, eng.submit(pk))
                         for grp, eng, pk in decoded]
            else:
                from ..serve.faults import submit_with_retry
                parts = [(grp, submit_with_retry(
                    lambda eng=eng, pk=pk: eng.submit(pk), self._retry,
                    stats=eng.stats)) for grp, eng, pk in decoded]
            return LookupRoundFuture(self._n_bins,
                                     self._server.entry_size, parts)

    def drain(self) -> None:
        """Resolve every outstanding dispatch across all group engines."""
        for _, _, eng in self._engines:
            eng.drain()

    def stats(self) -> dict:
        """Per-group engine counters, keyed "n<bin size>xG<group size>"."""
        return {"n%dxG%d" % (n, len(grp.idxs)): eng.stats.as_dict()
                for n, grp, eng in self._engines}

    def counters(self):
        """All group engines' counters folded into ONE
        ``EngineCounters`` (``merge``): the stream-level record —
        total dispatches, pooled latency ring, shed/deadline counts —
        without hand-copying fields per group."""
        from ..utils.profiling import EngineCounters
        agg = EngineCounters()
        for _, _, eng in self._engines:
            agg.merge(eng.stats)
        return agg


class PrivateLookupClient:
    """Generates per-bin keys for a planned fetch and recovers entries.

    ``make_queries`` is the production path: one *vectorized* batched
    keygen call per (n, G) size group (``keygen.gen_batched`` /
    ``radix4.gen_batched_r4`` / ``sqrtn.gen_sqrt_batched``) instead of
    the pre-PR per-bin ``DPF.gen`` Python loop;
    ``make_queries_scalar`` keeps that loop as the fuzz oracle
    (bit-identical keys under pinned seeds).  ``scheme``/``radix``
    mirror the server's arguments — with "auto", each size group's
    construction resolves from the scheme tuning cache on both sides,
    so ``entry_size`` is REQUIRED then and must be the server table's
    width (it is part of the cache key; a mismatch would resolve a
    different construction than the server's)."""

    def __init__(self, bins, bin_sizes, prf=None, radix: int = 2,
                 scheme: str = "logn", entry_size: int | None = None):
        from ..api import DPF
        _check_construction_args(scheme, radix)
        if scheme == "auto" and entry_size is None:
            raise ValueError(
                "scheme='auto' resolves constructions from the tuning "
                "cache keyed on the table's entry width — pass "
                "entry_size=<server table width>")
        if entry_size is None:
            entry_size = DPF.ENTRY_SIZE  # unused outside auto resolution
        self.prf_method = DPF.DEFAULT_PRF if prf is None else prf
        self.radix = radix
        self.scheme = scheme
        self.entry_size = entry_size
        self.bins = [sorted(b) for b in bins]
        self.bin_sizes = list(bin_sizes)
        self.index_to_bin = {}
        for bi, b in enumerate(self.bins):
            for pos, idx in enumerate(b):
                self.index_to_bin[idx] = (bi, pos)
        # size groups in bin order — mirrors the server's grouping, so
        # the per-group construction resolution agrees on (n, G)
        self._size_groups = {}
        for bi, n in enumerate(self.bin_sizes):
            self._size_groups.setdefault(n, []).append(bi)
        self._constructions = {
            n: _resolve_construction(scheme, radix, n, len(idxs),
                                     entry_size, self.prf_method)
            for n, idxs in self._size_groups.items()}
        self._scalar_dpfs = {}

    def group_constructions(self) -> dict:
        """{bin size n: (scheme, radix)} — must equal the server's."""
        return dict(self._constructions)

    def _plan(self, wanted):
        plan = [None] * len(self.bins)
        for idx in wanted:
            if idx in self.index_to_bin:
                bi, _ = self.index_to_bin[idx]
                if plan[bi] is None:
                    plan[bi] = idx
        return plan

    def make_queries(self, wanted, seeds=None):
        """Pick <=1 wanted index per bin; others get a dummy (position 0).

        Returns (keys for server A, keys for server B, plan) where plan[bin]
        is the table index retrieved there (or None for dummy queries —
        indistinguishable from real ones to each server).  Keys are
        generated per size group by the batched generators — one
        vectorized call per (n, G) group.  ``seeds``: optional per-bin
        DRBG seed list (None = fresh entropy; tests pin it for
        bit-parity with ``make_queries_scalar``).
        """
        from ..api import gen_batched_binary
        from ..core import radix4, sqrtn
        plan = self._plan(wanted)
        pos = [self.index_to_bin[t][1] if t is not None else 0
               for t in plan]
        ka = [None] * len(self.bins)
        kb = [None] * len(self.bins)
        for n, idxs in self._size_groups.items():
            sch, rad = self._constructions[n]
            alphas = [pos[bi] for bi in idxs]
            sd = None if seeds is None else [seeds[bi] for bi in idxs]
            if sch == "sqrtn":
                wa, wb = sqrtn.gen_sqrt_batched(
                    alphas, n, sd, prf_method=self.prf_method)
            elif rad == 4:
                wa, wb = radix4.gen_batched_r4(
                    alphas, n, sd, prf_method=self.prf_method)
            else:
                wa, wb = gen_batched_binary(alphas, n, sd,
                                            self.prf_method)
            for p, bi in enumerate(idxs):
                ka[bi] = wa[p]
                kb[bi] = wb[p]
        return ka, kb, plan

    def _scalar_dpf(self, sch: str, rad: int):
        from ..api import DPF
        key = (sch, rad)
        if key not in self._scalar_dpfs:
            if rad == 4:
                from ..utils.config import EvalConfig
                self._scalar_dpfs[key] = DPF(config=EvalConfig(
                    prf_method=self.prf_method, radix=4))
            else:
                self._scalar_dpfs[key] = DPF(prf=self.prf_method,
                                             scheme=sch)
        return self._scalar_dpfs[key]

    def make_queries_scalar(self, wanted, seeds=None):
        """The pre-batched per-bin ``DPF.gen`` loop, kept as the fuzz
        oracle (and the benchmark's keygen baseline): byte-identical
        keys to ``make_queries`` under the same ``seeds``."""
        plan = self._plan(wanted)
        ka, kb = [], []
        for bi, target in enumerate(plan):
            pos = self.index_to_bin[target][1] if target is not None else 0
            n = self.bin_sizes[bi]
            sch, rad = self._constructions[n]
            dpf = self._scalar_dpf(sch, rad)
            k1, k2 = dpf.gen(pos, n,
                             seed=None if seeds is None else seeds[bi])
            ka.append(k1)
            kb.append(k2)
        return ka, kb, plan

    def recover(self, shares_a, shares_b, plan):
        """-> dict {table index: entry row} for the non-dummy queries."""
        diff = (np.asarray(shares_a, np.int64)
                - np.asarray(shares_b, np.int64)).astype(np.int32)
        return {target: diff[bi] for bi, target in enumerate(plan)
                if target is not None}
