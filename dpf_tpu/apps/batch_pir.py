"""Batch-PIR scheduling optimizer: hot/cold split, co-location, binning.

Capability port of the reference's batch-PIR layer
(``paper/experimental/batch_pir/batch_pir_optimization.py:24-267``): given
train/validation *access patterns* (lists of index sets, e.g. the embedding
rows a user's inference touches), plan private batched lookups that maximize
the fraction of needed entries recovered under a budget of DPF queries:

* **hot/cold split** — the most frequently accessed ``cache_fraction`` of
  entries form a small "hot" table served with cheaper queries (ref ``:66-83``).
* **binning** — each table is cut into bins; one DPF query retrieves exactly
  one entry per bin, so a batch of needed indices spread over many bins is
  served by few queries (ref ``:49-64``).
* **co-location** — entries frequently co-accessed with x are stored in x's
  row, so recovering x recovers them for free (ref ``:198-248``).
* **cost model** — ``DPFCost(computation, upload, download)`` with the same
  2-KB/log2(n) key-size accounting (ref ``:85-88,187-194``).

Beyond the reference (which only *models* the protocol), ``PrivateLookupClient``
/ ``PrivateLookupServer`` execute the planned queries for real through the
TPU DPF backend.
"""

from __future__ import annotations

import json
import os
from collections import Counter, defaultdict
from dataclasses import asdict, dataclass, field

import numpy as np


@dataclass(frozen=True)
class HotColdConfig:
    cache_size_fraction: float = 1.0


@dataclass(frozen=True)
class CollocateConfig:
    num_collocate: int = 0


@dataclass(frozen=True)
class PIRConfig:
    bin_fraction: float = 0.1      # fraction of a table forming one bin
    entry_size_bytes: int = 256
    queries_to_hot: int = 1
    queries_to_cold: int = 0


@dataclass
class DPFCost:
    computation: int = 0
    upload_communication: int = 0
    download_communication: int = 0

    def _asdict(self):
        return asdict(self)


def dpf_key_cost_bytes(table_size: int) -> int:
    """Upload bytes per query: 16 B x 4 x log2(n) (ref ``:85-88``)."""
    if table_size <= 1:
        return 0
    return int(np.ceil(16 * 4 * np.log2(table_size)))


class BatchPIROptimize:
    """Plan (and cost) private batched lookups over access patterns."""

    def __init__(self, train_set, validation_set,
                 hotcold_config: HotColdConfig = HotColdConfig(),
                 collocate_config: CollocateConfig = CollocateConfig(),
                 pir_config: PIRConfig = PIRConfig(),
                 collocate_cache: str | dict | None = None):
        self.hotcold_config = hotcold_config
        self.collocate_config = collocate_config
        self.pir_config = pir_config
        self.train = [list(s) for s in train_set]
        self.val = [list(s) for s in validation_set]

        self._count_accesses()
        self._split_hot_cold()
        self._build_collocation(collocate_cache)
        self._build_bins()
        self.accuracy_stats = None
        self.cost = DPFCost()

    # -------------------------------------------------------- statistics

    def _count_accesses(self):
        self.embedding_counts = Counter()
        for idx_set in self.train:
            self.embedding_counts.update(idx_set)
        self.all_embedding_indices = set(self.embedding_counts)
        for idx_set in self.val:
            self.all_embedding_indices.update(idx_set)
        self.num_embeddings = len(self.all_embedding_indices)

    def _split_hot_cold(self):
        frac = self.hotcold_config.cache_size_fraction
        n_hot = int(frac * self.num_embeddings)
        by_freq = sorted(self.all_embedding_indices,
                         key=lambda x: self.embedding_counts[x], reverse=True)
        self.hot_table = by_freq[:n_hot]
        self.cold_table = by_freq[n_hot:]
        # shuffle within each table so bins are unbiased — must be stable
        # ACROSS PROCESSES (client and server derive bins independently),
        # so use a keyed digest, not the per-process-salted builtin hash()
        def stable_key(x):
            import hashlib
            return hashlib.sha256(str(x).encode()).digest()
        self.hot_table.sort(key=stable_key)
        self.cold_table.sort(key=stable_key)

    def _build_collocation(self, cache):
        """Top co-accessed neighbors per entry (cacheable: it is O(sum k^2))."""
        k = self.collocate_config.num_collocate
        if isinstance(cache, str) and os.path.exists(cache):
            with open(cache) as f:
                loaded = json.load(f)
            self.collocation_map = {int(i): v for i, v in loaded.items()}
            return
        if isinstance(cache, dict):
            self.collocation_map = {int(i): v for i, v in cache.items()}
            return
        co = defaultdict(Counter)
        if k > 0:
            for idx_set in self.train:
                uniq = set(idx_set)
                for src in uniq:
                    for dst in uniq:
                        if src != dst:
                            co[src][dst] += 1
        self.collocation_map = {
            idx: [d for d, _ in co[idx].most_common(k)] if idx in co else []
            for idx in self.all_embedding_indices}
        if isinstance(cache, str):
            with open(cache, "w") as f:
                json.dump(self.collocation_map, f)

    def _build_bins(self):
        def bins_of(table):
            if not table:
                return [], 0
            per_bin = max(1, int(len(table) * self.pir_config.bin_fraction))
            return ([set(table[i:i + per_bin])
                     for i in range(0, len(table), per_bin)], per_bin)

        self.hot_table_bins, self.hot_entries_per_bin = bins_of(self.hot_table)
        self.cold_table_bins, self.cold_entries_per_bin = \
            bins_of(self.cold_table)

    # -------------------------------------------------------------- fetch

    def fetch(self, batch_indices):
        """Greedy multi-query plan for one batch of needed indices.

        Returns (recovered index set, DPFCost).  Each query round retrieves
        at most one entry per bin; the most-needed unrecovered candidate in
        each bin wins (ref ``:144-196``).
        """
        counts = Counter(batch_indices)
        targets = set(counts)
        recovered = set()

        def one_query(bins):
            for b in bins:
                cands = b & targets
                if not cands:
                    continue
                best = max(cands, key=lambda x: (-1, 0) if x in recovered
                           else (0, counts[x]))
                if best not in recovered:
                    recovered.add(best)

        for _ in range(self.pir_config.queries_to_hot):
            one_query(self.hot_table_bins)
        for _ in range(self.pir_config.queries_to_cold):
            one_query(self.cold_table_bins)

        collocated = set()
        for idx in recovered:
            collocated.update(self.collocation_map.get(idx, []))
        all_recovered = recovered | collocated

        qh, qc = (self.pir_config.queries_to_hot,
                  self.pir_config.queries_to_cold)
        cost = DPFCost(
            computation=qh * len(self.hot_table) + qc * len(self.cold_table),
            upload_communication=(
                qh * dpf_key_cost_bytes(self.hot_entries_per_bin)
                * len(self.hot_table_bins)
                + qc * dpf_key_cost_bytes(self.cold_entries_per_bin)
                * len(self.cold_table_bins)),
            download_communication=(
                (qh * len(self.hot_table_bins)
                 + qc * len(self.cold_table_bins))
                * self.pir_config.entry_size_bytes))
        return all_recovered, cost

    # ---------------------------------------------------------- evaluate

    def evaluate(self, limit=None):
        """Fraction-of-batch-recovered over the validation access patterns."""
        self.percentage_of_query_recovered = []
        for val in self.val[:limit]:
            if not val:
                continue
            recovered, self.cost = self.fetch(val)
            hit = set(x for x in recovered if x in val)
            self.percentage_of_query_recovered.append(
                len(hit) / len(set(val)))
        return self.percentage_of_query_recovered

    def evaluate_with_model(self, dataset_module, limit=None):
        """Evaluate + downstream model accuracy with unrecovered embeddings
        masked (the accuracy-vs-PIR-budget experiment, ref ``:114-118``)."""
        self.evaluate(limit=limit)
        self.accuracy_stats = dataset_module.evaluate(self)
        return self.accuracy_stats

    def summarize_evaluation(self):
        p = self.percentage_of_query_recovered
        summary = {
            "pir_config": asdict(self.pir_config),
            "hotcold_config": asdict(self.hotcold_config),
            "collocate_config": asdict(self.collocate_config),
            "mean_recovered": float(np.mean(p)),
            **{"recovered_p_%d" % q: float(np.percentile(p, q))
               for q in (0, 5, 10, 50, 90, 95)},
            "cost": self.cost._asdict(),
            "accuracy_stats": self.accuracy_stats,
            "extra": {
                "hot_table_size": len(self.hot_table),
                "cold_table_size": len(self.cold_table),
                "hot_table_entries_per_bin": self.hot_entries_per_bin,
                "cold_table_entries_per_bin": self.cold_entries_per_bin,
            },
        }
        return summary


# ---------------------------------------------------------------------------
# Real execution of a batch-PIR plan through the TPU DPF backend.
# (The reference models the protocol analytically; this runs it.)
# ---------------------------------------------------------------------------

def _pad_pow2(n, lo=128):
    from ..core.u128 import next_pow2
    return next_pow2(max(n, lo))


class PrivateLookupServer:
    """Holds one bin-structured table; answers DPF queries per bin.

    Each bin is padded to a power-of-two mini-table; bins of equal padded
    size are stacked so one batched per-key-table evaluation
    (``expand.expand_and_contract_per_key_tables``) answers one query round
    across all of them in a single device dispatch — the reference's layer
    loops bins on the host instead.
    """

    def __init__(self, table: np.ndarray, bins, prf=None, radix: int = 2,
                 mesh=None):
        """mesh: optional ``jax.sharding.Mesh`` — equal-size bin groups
        are embarrassingly parallel, so the stacked [G, n, E] tables and
        the per-bin key batch shard over ALL mesh axes flattened onto
        the group axis (G padded with zero bins to the device count);
        one query round then runs as one SPMD dispatch across the mesh.
        The reference has no multi-device batch-PIR at all."""
        from ..api import DPF
        from ..core import expand, radix4
        self.prf_method = DPF.DEFAULT_PRF if prf is None else prf
        assert radix in (2, 4)
        self.radix = radix
        self.mesh = mesh
        self.entry_size = table.shape[1]
        self.bins = [sorted(b) for b in bins]
        self.bin_sizes = []
        padded_tables = []
        for b in self.bins:
            sub = table[b] if b else np.zeros((1, self.entry_size), np.int32)
            n = _pad_pow2(len(sub))
            padded = np.zeros((n, self.entry_size), np.int32)
            padded[:len(sub)] = sub
            padded_tables.append(padded)
            self.bin_sizes.append(n)

        def permute(padded):
            if radix == 4:
                perm = radix4.mixed_reverse_indices(
                    radix4.arities(padded.shape[0]))
                return np.ascontiguousarray(padded[perm])
            return expand.permute_table(padded)

        # group bins by padded size -> one stacked [G, n, E] device array
        # each; with a mesh, G pads to the device count and shards
        import jax.numpy as jnp
        self._groups = {}  # n -> (bin indices, stacked tables, group pad)
        for bi, (n, padded) in enumerate(zip(self.bin_sizes, padded_tables)):
            self._groups.setdefault(n, [[], []])
            self._groups[n][0].append(bi)
            self._groups[n][1].append(permute(padded))
        out = {}
        for n, (idxs, tbls) in self._groups.items():
            stacked = np.stack(tbls)
            pad = 0
            if mesh is not None:
                pad = (-stacked.shape[0]) % mesh.size
                if pad:
                    stacked = np.concatenate(
                        [stacked, np.zeros((pad,) + stacked.shape[1:],
                                           np.int32)])
                stacked = self._shard(jnp.asarray(stacked))
            else:
                stacked = jnp.asarray(stacked)
            out[n] = (idxs, stacked, pad)
        self._groups = out

    def _shard(self, arr):
        """Shard axis 0 (the bin-group axis) over every mesh axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(tuple(self.mesh.axis_names),
                 *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _pad_keys(self, packed, pad):
        """Pad the packed key batch (axis 0) to the sharded group size by
        repeating the last key (answers land in zero-table rows that the
        caller slices away) and co-shard with the tables."""
        import jax.numpy as jnp
        out = []
        for a in packed:
            a = np.asarray(a)
            if pad:
                a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
            out.append(self._shard(jnp.asarray(a))
                       if self.mesh is not None else jnp.asarray(a))
        return out

    def answer(self, keys_per_bin):
        """keys_per_bin: one serialized key per bin -> [n_bins, E] shares."""
        from ..core import expand, keygen, radix4
        from ..core import prf as _prf
        from ..ops import matmul128
        out = np.zeros((len(self.bins), self.entry_size), np.int32)
        for n, (idxs, tables, gpad) in self._groups.items():
            if self.radix == 4:
                mk = [radix4.deserialize_mixed_key(keys_per_bin[bi])
                      for bi in idxs]
                for k in mk:
                    if k.n != n:
                        raise ValueError(
                            "key for bin of size %d got n=%d" % (n, k.n))
                cw1, cw2, last = self._pad_keys(
                    radix4.pack_mixed_keys(mk), gpad)
                shares = radix4.expand_and_contract_per_key_tables_mixed(
                    cw1, cw2, last, tables, n=n,
                    prf_method=self.prf_method,
                    chunk_leaves=expand.choose_chunk(n, len(mk)),
                    dot_impl=matmul128.default_impl(),
                    aes_impl=_prf._aes_pair_impl(),
                    round_unroll=_prf.ROUND_UNROLL)
                out[idxs] = np.asarray(shares)[:len(idxs)]
                continue
            flat = [keygen.deserialize_key(keys_per_bin[bi]) for bi in idxs]
            for fk in flat:
                if fk.n != n:
                    raise ValueError(
                        "key for bin of size %d got n=%d" % (n, fk.n))
            cw1, cw2, last = self._pad_keys(expand.pack_keys(flat), gpad)
            depth = n.bit_length() - 1
            shares = expand.expand_and_contract_per_key_tables(
                cw1, cw2, last, tables, depth=depth,
                prf_method=self.prf_method,
                chunk_leaves=expand.choose_chunk(n, len(flat)),
                dot_impl=matmul128.default_impl(),
                aes_impl=_prf._aes_pair_impl(),
                round_unroll=_prf.ROUND_UNROLL)
            out[idxs] = np.asarray(shares)[:len(idxs)]
        return out


class PrivateLookupClient:
    """Generates per-bin keys for a planned fetch and recovers entries."""

    def __init__(self, bins, bin_sizes, prf=None, radix: int = 2):
        from ..api import DPF
        if radix == 4:
            from ..utils.config import EvalConfig
            self.dpf = DPF(config=EvalConfig(
                prf_method=DPF.DEFAULT_PRF if prf is None else prf,
                radix=4))
        else:
            self.dpf = DPF(prf=prf)
        self.bins = [sorted(b) for b in bins]
        self.bin_sizes = bin_sizes
        self.index_to_bin = {}
        for bi, b in enumerate(self.bins):
            for pos, idx in enumerate(b):
                self.index_to_bin[idx] = (bi, pos)

    def make_queries(self, wanted):
        """Pick <=1 wanted index per bin; others get a dummy (position 0).

        Returns (keys for server A, keys for server B, plan) where plan[bin]
        is the table index retrieved there (or None for dummy queries —
        indistinguishable from real ones to each server).
        """
        plan = [None] * len(self.bins)
        for idx in wanted:
            if idx in self.index_to_bin:
                bi, _ = self.index_to_bin[idx]
                if plan[bi] is None:
                    plan[bi] = idx
        ka, kb = [], []
        for bi, target in enumerate(plan):
            pos = self.index_to_bin[target][1] if target is not None else 0
            k1, k2 = self.dpf.gen(pos, self.bin_sizes[bi])
            ka.append(k1)
            kb.append(k2)
        return ka, kb, plan

    def recover(self, shares_a, shares_b, plan):
        """-> dict {table index: entry row} for the non-dummy queries."""
        diff = (np.asarray(shares_a, np.int64)
                - np.asarray(shares_b, np.int64)).astype(np.int32)
        return {target: diff[bi] for bi, target in enumerate(plan)
                if target is not None}
