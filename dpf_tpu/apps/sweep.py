"""Grid sweep over batch-PIR configurations (reference ``sweep/sweep.py``).

Sweeps (hot/cold cache fraction x co-location x bin fraction x query
budgets), evaluates recovery percentiles (and optionally downstream model
accuracy), and writes one JSON result per config — the reference's
one-file-per-config protocol (``sweep/sweep.py:80-84``).
"""

from __future__ import annotations

import itertools
import json
import os

from .batch_pir import (BatchPIROptimize, CollocateConfig, HotColdConfig,
                        PIRConfig)

DEFAULT_GRID = {
    "cache_size_fraction": [0.25, 0.5, 1.0],
    "num_collocate": [0, 2],
    "bin_fraction": [0.05, 0.1, 0.3],
    "queries_to_hot": [1, 2, 4],
    "queries_to_cold": [0, 1],
}


def config_name(cfg: dict) -> str:
    return "_".join("%s=%s" % (k, cfg[k]) for k in sorted(cfg))


def run_sweep(train_patterns, val_patterns, out_dir=None, grid=None,
              eval_limit=None, model_eval=None, skip_existing=True):
    """Run the grid; returns list of summary dicts.

    model_eval: optional callable(optimizer) -> accuracy stats dict, hooked
    in as the downstream-model metric (reference `evaluate_real`).
    """
    grid = dict(DEFAULT_GRID, **(grid or {}))
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    results = []
    keys = sorted(grid)
    for values in itertools.product(*(grid[k] for k in keys)):
        cfg = dict(zip(keys, values))
        if cfg["cache_size_fraction"] >= 1.0 and cfg["queries_to_cold"] > 0:
            continue  # no cold table to query
        path = (os.path.join(out_dir, config_name(cfg) + ".json")
                if out_dir else None)
        if path and skip_existing and os.path.exists(path):
            with open(path) as f:
                results.append(json.load(f))
            continue
        opt = BatchPIROptimize(
            train_patterns, val_patterns,
            HotColdConfig(cfg["cache_size_fraction"]),
            CollocateConfig(cfg["num_collocate"]),
            PIRConfig(bin_fraction=cfg["bin_fraction"],
                      queries_to_hot=cfg["queries_to_hot"],
                      queries_to_cold=cfg["queries_to_cold"]))
        opt.evaluate(limit=eval_limit)
        if model_eval is not None:
            opt.accuracy_stats = model_eval(opt)
        summary = opt.summarize_evaluation()
        summary["config"] = cfg
        results.append(summary)
        if path:
            with open(path, "w") as f:
                json.dump(summary, f, indent=1)
    return results
