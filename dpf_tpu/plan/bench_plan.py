"""Capacity-planning benchmark: twin fidelity, planner, autoscaler.

``benchmark.py --plan``.  Four gated legs over one probed cost table:

* **fidelity** — the headline gate.  The digital twin
  (``plan/twin.py``) simulates the IDENTICAL seeded traces the real
  open-loop harness (``serve/bench_load.replay``) replays through a
  real ``ServingEngine`` over the same bucket ladder, and the record
  gates predicted-vs-measured p99 (plain bursty + diurnal legs) and
  shed rate (admission-armed leg on the squeezed trace) within the
  documented tolerance band (``TOLERANCE``; rationale in
  docs/PLANNING.md "Fidelity tolerance band").  The twin runs with
  ``dispatch_blocking=True`` here — the cost table measures a blocking
  dispatch (``ServingEngine.probe``), which on the synchronous XLA-CPU
  backend is exactly what the client thread pays.
* **planner** — ``plan/capacity.plan_fleet`` headroom sweep; the
  record gates that the emitted curve is monotone in offered load
  (more qps never plans fewer engines — enforced by construction,
  asserted from the record).
* **autoscale (twin)** — ``plan/autoscale.AutoscalePolicy`` evaluated
  over a two-day diurnal trace (``loadgen.concat_traces``) with one
  injected engine death at the first peak; gates that the autoscaled
  fleet holds availability and p99-under-SLO while spending STRICTLY
  fewer engine-hours than the static peak-sized fleet on the same
  trace and fault plan.
* **autoscale (real)** — the same policy driving a ``ReplicaPool`` of
  real ``ServingEngine`` replicas: scale-up builds + warms a real
  engine, scale-down drains via ``ServingEngine.drain()`` then
  ``close()`` (post-close submit must raise ``EngineClosed``), every
  served batch equality-gated against the scalar oracle
  (``DPF.eval_cpu``), like every serving bench.

The committed CPU record is ``PLAN_r17.json``; the same command
produces the relay-TPU record.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --plan [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json

import numpy as np

from ..obs import FLIGHT, record_sections
from ..obs.metrics import register_planner
from ..serve import loadgen
from ..serve.bench_load import _batch_for, _gate, _key_pool, replay
from ..serve.engine import EngineClosed, ServingEngine
from ..utils.profiling import quantile
from .autoscale import AutoscalePolicy, ReplicaPool
from .capacity import plan_fleet, required_replicas
from .twin import PLAN_STATS, CostTable, FleetConfig, simulate

#: The documented fidelity tolerance band (docs/PLANNING.md).  The twin
#: predicts from per-bucket blocking-dispatch costs alone — it carries
#: no host-side decode/GC/scheduler noise — so the p99 gate allows a
#: relative error plus a fixed slack (the slack dominates for
#: light-load legs where p99 is a few service times; the relative term
#: dominates under queueing, where p99 is backlog-shaped and scales
#: with the cost-table error).  Shed rate is gated absolutely: both
#: sides shed by the same ring-p99/queue-depth triggers, so the rates
#: must land close even when individual latencies wobble.
TOLERANCE = {"p99_rel": 0.50, "p99_slack_ms": 40.0, "shed_abs": 0.15}


def _p99_ms(lats) -> float | None:
    if not lats:
        return None
    ms = sorted(x * 1e3 for x in lats)
    return round(quantile(ms, 0.99, presorted=True), 3)


def _p99_within(real_ms, twin_ms, tol) -> bool:
    if real_ms is None or twin_ms is None:
        return real_ms is None and twin_ms is None
    return (abs(twin_ms - real_ms)
            <= tol["p99_slack_ms"] + tol["p99_rel"] * real_ms)


def _real_leg(make_engine, trace, pools, label, *, window, reps) -> dict:
    """Replay ``trace`` through a real engine (fresh per rep — the
    admission ring must start clean); keep the best-qps rep, the same
    selection rule as the --load legs."""
    total_q = loadgen.total_queries(trace)
    best = None
    for _ in range(max(1, reps)):
        eng = make_engine()

        def submit(a, j):
            keys, _ = _batch_for(pools[label], j, a.batch)
            return eng.submit(keys)

        lats, done, makespan, sheds, shed_q = replay(trace, submit,
                                                     window=window)
        offered = len(trace)
        qps = int((total_q - shed_q) / makespan) if makespan else 0
        leg = {
            "qps": qps, "makespan_s": round(makespan, 4),
            "p99_ms": _p99_ms(lats),
            "shed_batches": sheds, "shed_queries": shed_q,
            "shed_rate": round(sheds / offered, 4) if offered else 0.0,
            "_done": done,
        }
        if best is None or qps > best["qps"]:
            best = leg
    return best


def _twin_view(summary: dict) -> dict:
    """The slice of a twin summary the fidelity legs compare/record."""
    return {k: summary[k] for k in ("qps", "makespan_s", "p99_ms",
                                    "shed_batches", "shed_rate",
                                    "availability")}


def _autoscale_twin(cost, label: str, cap: int, sizes, *, window: int,
                    seed: int, max_replicas: int) -> dict:
    """The autoscaler's twin leg: two diurnal days + one engine death.

    The trace is generated at a fixed nominal rate and then
    ``scale_rate``-compressed so the PEAK offers ~2.5x one replica's
    service capacity (from the cost table) — the leg is calibrated in
    service units, so it exercises real scale-up pressure on any
    backend speed.  All policy clocks (decision cadence, cooldown,
    spin-up, rebuild) are sized relative to the compressed day for the
    same reason."""
    cap_bucket = sizes[-1]
    svc = max(cost.service_s(label, cap_bucket), 1e-7)
    nominal_peak, day_s = 40.0, 8.0
    day = loadgen.diurnal_trace(base_rate=nominal_peak / 10,
                                peak_rate=nominal_peak, period_s=day_s,
                                duration_s=day_s, cap=cap, seed=seed)
    two_days = loadgen.concat_traces(day, day)
    # compress so peak offered load = 2.5x one replica's capacity
    factor = 2.5 / (nominal_peak * svc)
    trace = loadgen.scale_rate(two_days, factor)
    span_s = trace[-1].t if trace else 1.0
    slo_s = 50 * svc
    dt = span_s / 64
    # one engine death at the first diurnal peak (the worst moment)
    peak_t = trace[len(day) // 2].t if len(day) // 2 < len(trace) else 0
    j_death = next((j for j, a in enumerate(trace) if a.t >= peak_t),
                   len(trace) // 4)
    fault_plan = {"seed": seed,
                  "specs": [{"kind": "engine_death", "start": j_death,
                             "p": 1.0}]}

    fleet_kw = dict(bucket_sizes=sizes, window=window,
                    spinup_s=dt / 2, rebuild_s=4 * dt,
                    retry_max_attempts=4)
    # the static comparator: the planner's peak-sized fleet, up for the
    # whole two days (what you deploy without an autoscaler)
    static_req = required_replicas(
        trace, cost, label=label, slo_s=slo_s, fleet_kw=dict(fleet_kw),
        seed=seed, max_replicas=max_replicas)
    r_static = max(2, static_req.replicas)
    static_fleet = FleetConfig(replicas={label: r_static},
                               dispatch_blocking=False, slo_s=slo_s,
                               **fleet_kw)
    static = simulate(trace, cost, static_fleet, seed=seed,
                      fault_plan=fault_plan,
                      record_events=False).summary()

    policy = AutoscalePolicy(min_replicas=1,
                             max_replicas=max(r_static + 1, 4),
                             decide_every_s=dt, cooldown_s=2 * dt,
                             p99_low_frac=0.6)
    auto_fleet = FleetConfig(replicas={label: 1},
                             dispatch_blocking=False, slo_s=slo_s,
                             **fleet_kw)
    auto = simulate(trace, cost, auto_fleet, seed=seed,
                    fault_plan=fault_plan, autoscaler=policy,
                    record_events=False).summary()

    slo_ms = round(slo_s * 1e3, 3)
    gates = {
        "availability": auto["availability"] >= 0.99,
        "p99_under_slo": (auto["p99_ms"] is not None
                          and auto["p99_ms"] <= slo_ms),
        "fewer_engine_hours": (auto["engine_hours"]
                               < static["engine_hours"]),
        "scaled_up": auto["autoscale"]["ups"] >= 1,
        "death_injected": auto["faults_injected"].get("engine_death",
                                                      0) == 1,
    }
    auto_rec = dict(auto)
    auto_rec["autoscale"] = {
        "ups": auto["autoscale"]["ups"],
        "downs": auto["autoscale"]["downs"],
        "log": auto["autoscale"]["log"][:24],   # bounded in the record
    }
    return {
        "trace": {"kind": "2x diurnal + engine_death", "seed": seed,
                  "arrivals": len(trace), "death_at_arrival": j_death,
                  "rate_scale": round(factor, 4),
                  "peak_util_target": 2.5},
        "slo_ms": slo_ms,
        "static_replicas": r_static,
        "static": {k: static[k] for k in
                   ("availability", "p99_ms", "engine_hours",
                    "shed_rate")},
        "autoscaled": auto_rec,
        "engine_hours_saved": round(
            static["engine_hours"] - auto["engine_hours"], 6),
        "policy": policy.as_dict(),
        "gates": gates,
        "ok": all(gates.values()),
    }


def _autoscale_real(router, label: str, pools, cap: int, *,
                    window: int, seed: int, slo_s: float) -> dict:
    """The autoscaler's real-engine smoke: the same policy driving a
    ``ReplicaPool`` of real engines over a short bursty trace, then a
    forced up/down cycle so both transitions run even if the policy
    held.  Gated on oracle equality of every served batch and on the
    post-close ``EngineClosed`` rejection."""
    srv = router.server(label)
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3,
                             decide_every_s=0.05, cooldown_s=0.1)
    pool = ReplicaPool(
        lambda: ServingEngine(srv, max_in_flight=2,
                              buckets=router.buckets, warmup=True,
                              label=label),
        policy=policy, initial=1)
    trace = loadgen.bursty_trace(on_rate=30.0, off_rate=2.0, on_s=0.5,
                                 off_s=0.5, duration_s=1.5, cap=cap,
                                 seed=seed)

    def submit(a, j):
        pool.step(slo_s=slo_s)      # the serving-loop control tick
        keys, _ = _batch_for(pools[label], j, a.batch)
        return pool.submit(keys)

    lats, done, makespan, sheds, _ = replay(trace, submit,
                                            window=window)
    pool.scale_up()                 # force both transitions
    forced_down = pool.scale_down()
    rejections = _gate(done, pools, lambda f: label)
    eng0 = pool.replicas[0]
    engine_seconds = pool.close()
    try:
        eng0.submit([])
        closed_ok = False
    except EngineClosed:
        closed_ok = True
    ok = (rejections == 0 and forced_down and closed_ok
          and pool.scale_ups >= 1 and pool.scale_downs >= 1
          and sheds == 0)
    return {
        "arrivals": len(trace), "p99_ms": _p99_ms(lats),
        "makespan_s": round(makespan, 4),
        "scale_ups": pool.scale_ups, "scale_downs": pool.scale_downs,
        "engine_seconds": round(engine_seconds, 4),
        "gate_rejections": rejections,
        "closed_rejects_submit": closed_ok,
        "ok": ok,
    }


def plan_bench(n=4096, entry_size=16, cap=128, prf=0, *, seed=11,
               duration_s=6.0, on_rate=160.0, slo_ms=250.0, reps=2,
               distinct=16, window=8, max_replicas=16,
               quiet=False) -> dict:
    """Run the four planning legs and return the ``--plan`` record."""
    from ..serve.router import SchemeRouter, resolve_sticky
    from ..tune.serve_tune import cached_cost_table

    FLIGHT.clear()      # scope the embedded flight tail to this bench
    register_planner(PLAN_STATS)
    table = np.random.default_rng(seed ^ 0x91a7).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    router = SchemeRouter(table, prf=prf, cap=cap, probe=True)
    # the construction under test: the sticky resolution (what a
    # DPF(scheme="auto") deployment pins), same rule as --load
    label, resolved_from = resolve_sticky(n, entry_size, prf, cap)
    srv = router.server(label)
    pools = {label: _key_pool(srv, n, distinct,
                              b"plan-%s" % label.encode())}
    # the twin's service-time input: the probe-seeded live cost model
    # (satellite of the same snapshot --load now embeds); the tuning-
    # cache recovery path rides along for auditability
    cost_snapshot = router.cost_table()
    cached = cached_cost_table(n=n, entry_size=entry_size, cap=cap,
                               prf_method=prf)
    cost = CostTable(cost_snapshot)
    sizes = tuple(router.buckets.sizes)
    slo_s = slo_ms / 1e3

    # ---- fidelity: twin vs the real harness on identical traces ------
    bursty = loadgen.bursty_trace(on_rate=on_rate, off_rate=2.0,
                                  on_s=1.0, off_s=2.0,
                                  duration_s=duration_s, cap=cap,
                                  seed=seed, n=n)
    diurnal = loadgen.diurnal_trace(base_rate=4.0,
                                    peak_rate=on_rate / 2,
                                    period_s=duration_s / 2,
                                    duration_s=duration_s, cap=cap,
                                    seed=seed, n=n)
    squeezed = loadgen.squeeze(bursty, 4.0)
    depth = max(2, window // 2)
    plain_kw = dict(max_in_flight=2, buckets=router.buckets,
                    warmup=True, label=label)
    shed_kw = dict(plain_kw, slo_s=slo_s, max_queue_depth=depth,
                   shed=True)
    plain_fleet = FleetConfig(replicas={label: 1}, bucket_sizes=sizes,
                              max_in_flight=2, window=window)
    shed_fleet = FleetConfig(replicas={label: 1}, bucket_sizes=sizes,
                             max_in_flight=2, window=window,
                             slo_s=slo_s, max_queue_depth=depth,
                             shed=True)
    specs = [
        ("bursty", bursty, plain_kw, plain_fleet, "p99"),
        ("diurnal", diurnal, plain_kw, plain_fleet, "p99"),
        ("bursty_4x_shed", squeezed, shed_kw, shed_fleet, "shed"),
    ]
    legs, violations, done_all = [], 0, []
    for name, trace, eng_kw, fleet, gated in specs:
        real = _real_leg(lambda: ServingEngine(srv, **eng_kw), trace,
                         pools, label, window=window, reps=reps)
        done_all.append(real.pop("_done"))
        twin = _twin_view(simulate(trace, cost, fleet, seed=seed,
                                   record_events=False).summary())
        leg = {"name": name, "arrivals": len(trace),
               "queries": loadgen.total_queries(trace),
               "gated": gated, "real": real, "twin": twin}
        if gated == "p99":
            leg["p99_within"] = _p99_within(real["p99_ms"],
                                            twin["p99_ms"], TOLERANCE)
            ok = leg["p99_within"]
        else:
            leg["shed_within"] = (abs(twin["shed_rate"]
                                      - real["shed_rate"])
                                  <= TOLERANCE["shed_abs"])
            ok = leg["shed_within"]
        if not ok:
            violations += 1
        legs.append(leg)
    fidelity = {
        "dispatch_model": "blocking",
        "window": window,
        "tolerance": TOLERANCE,
        "legs": legs,
        "violations": violations,
        "checked": violations == 0,
    }
    p99_errs = [abs(leg["twin"]["p99_ms"] - leg["real"]["p99_ms"])
                / leg["real"]["p99_ms"]
                for leg in legs if leg["gated"] == "p99"
                and leg["real"]["p99_ms"]]
    worst_rel = round(max(p99_errs), 4) if p99_errs else None

    # ---- planner: headroom sweep, monotone by construction -----------
    planner = plan_fleet(bursty, cost, label=label, slo_s=slo_s,
                         load_scales=(0.5, 1.0, 1.5, 2.0), seed=seed,
                         fleet_kw=dict(bucket_sizes=sizes,
                                       window=window),
                         max_replicas=max_replicas)

    # ---- autoscaler: twin (gated) + real-engine smoke ----------------
    auto_twin = _autoscale_twin(cost, label, cap, sizes, window=window,
                                seed=seed, max_replicas=max_replicas)
    auto_real = _autoscale_real(router, label, pools, cap,
                                window=window, seed=seed, slo_s=slo_s)

    # ---- oracle equality over every real served batch ----------------
    rejections = sum(_gate(done, pools, lambda f: label)
                     for done in done_all)
    rejections += auto_real["gate_rejections"]

    record = {
        "metric": "digital-twin capacity planning: twin fidelity vs "
                  "the real open-loop harness + planner + autoscaler "
                  "(entries=%d, entry_size=%d, prf=%d, construction="
                  "%s, cap=%d, slo=%dms, 1 device)"
                  % (n, entry_size, prf, label, cap, int(slo_ms)),
        "value": worst_rel,
        "unit": "worst twin-vs-measured p99 relative error",
        "construction": label,
        "resolved_from": resolved_from,
        "slo_ms": slo_ms,
        "trace": {"kind": "bursty+diurnal", "seed": seed,
                  "duration_s": duration_s, "on_rate": on_rate,
                  "cap": cap, "window": window, "reps": reps},
        # the twin's exact inputs, embedded so every number above is
        # reproducible from the record alone (simulate() is a pure
        # function of these)
        "cost_table": cost_snapshot,
        "cost_table_cached": cached,
        "fleet": plain_fleet.as_dict(),
        "fidelity": fidelity,
        "planner": planner,
        "autoscale_twin": auto_twin,
        "autoscale_real": auto_real,
        "plan_stats": {
            "twin_runs": PLAN_STATS.twin_runs,
            "sim_arrivals": PLAN_STATS.sim_arrivals,
            "sweeps": PLAN_STATS.sweeps,
            "scale_ups": PLAN_STATS.scale_ups,
            "scale_downs": PLAN_STATS.scale_downs,
        },
        "gate_rejections": rejections,
        "checked": (violations == 0 and bool(planner["monotone"])
                    and auto_twin["ok"] and auto_real["ok"]
                    and rejections == 0),
    }
    record["obs"] = record_sections()
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--duration", type=float, default=6.0,
                    help="fidelity trace duration in seconds")
    ap.add_argument("--on-rate", type=float, default=160.0,
                    help="burst arrival rate of the fidelity trace")
    ap.add_argument("--slo-ms", type=float, default=250.0)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): every leg and "
                         "gate in seconds, no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.dryrun:
        record = plan_bench(n=512, entry_size=8, cap=16, prf=args.prf,
                            seed=args.seed, duration_s=1.5,
                            on_rate=30.0, slo_ms=args.slo_ms, reps=1,
                            distinct=8, max_replicas=6)
    else:
        record = plan_bench(n=args.n, entry_size=args.entry_size,
                            cap=args.cap, prf=args.prf, seed=args.seed,
                            duration_s=args.duration,
                            on_rate=args.on_rate, slo_ms=args.slo_ms,
                            reps=args.reps, max_replicas=16)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
