"""Reactive autoscaling: scale engine replicas on EWMA-util/p99 signals.

Two halves share one policy:

* ``AutoscalePolicy`` — the pure decision function.  Stdlib-only, no
  clocks: callers feed it (utilization over the last decision window,
  ring p99, SLO, replica count, seconds since the last change) and it
  answers "up" / "down" / None.  The digital twin evaluates it over
  virtual time (``twin.simulate(..., autoscaler=policy)``); the bench's
  autoscale leg gates it against the static peak-sized fleet on
  engine-hours.
* ``ReplicaPool`` — the same policy run against REAL
  ``serve.ServingEngine`` replicas: scale-up builds + warms a fresh
  engine from a factory, scale-down drains via the existing
  ``ServingEngine.drain()`` path and then ``close()``s it (the clean
  post-drain rejection added for exactly this), preemption is the PR-8
  engine-death fault arriving through the pool's submit path.  All
  serve imports are lazy (inside methods), so importing this module
  stays jax-free — the zero-JAX subprocess test covers it.

Policy shape (docs/PLANNING.md "Autoscale policy knobs"): scale UP when
EWMA utilization crosses ``high_util`` or ring p99 crosses
``p99_high_frac`` of the SLO; scale DOWN only when utilization is
under ``low_util`` AND p99 is comfortably inside the SLO.  ``cooldown_s``
rate-limits changes (a scale-up's ``spinup_s`` warmup must land before
the next decision can react to it); min/max replica bounds are hard.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class AutoscalePolicy:
    """The pure scale-up/down decision function (see module docstring).

    ``decide_every_s`` is the decision cadence the twin (or a real
    control loop) samples signals at; utilization is EWMA-smoothed here
    with ``ewma_alpha`` so one idle window does not flap the fleet."""
    min_replicas: int = 1
    max_replicas: int = 8
    high_util: float = 0.75
    low_util: float = 0.30
    p99_high_frac: float = 0.9
    p99_low_frac: float = 0.5
    decide_every_s: float = 0.25
    cooldown_s: float = 0.5
    ewma_alpha: float = 0.5

    def __post_init__(self):
        if not 0 < self.ewma_alpha <= 1:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self._util_ewma = None

    def decide(self, *, util: float, p99_s: float | None,
               slo_s: float | None, replicas: int,
               since_change_s: float) -> str | None:
        """One decision: "up", "down", or None (hold)."""
        u = max(0.0, float(util))
        self._util_ewma = (u if self._util_ewma is None else
                           self.ewma_alpha * u
                           + (1 - self.ewma_alpha) * self._util_ewma)
        if since_change_s < self.cooldown_s:
            return None
        p99_hot = (p99_s is not None and slo_s is not None
                   and p99_s > self.p99_high_frac * slo_s)
        p99_cool = (p99_s is None or slo_s is None
                    or p99_s < self.p99_low_frac * slo_s)
        if ((self._util_ewma > self.high_util or p99_hot)
                and replicas < self.max_replicas):
            return "up"
        if (self._util_ewma < self.low_util and p99_cool
                and replicas > self.min_replicas):
            return "down"
        return None

    def reset(self) -> None:
        self._util_ewma = None

    def as_dict(self) -> dict:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}


class ReplicaPool:
    """The autoscaler's real-engine leg: a pool of ``ServingEngine``
    replicas over ONE prepared server, scaled by ``AutoscalePolicy``.

    ``factory()`` builds a fresh engine (the caller closes over the
    prepared server + shared bucket ladder, so every replica serves
    the same table through the same programs — scale-up pays warmup,
    not re-upload).  ``submit`` routes to the least-loaded alive
    replica; ``scale_down`` drains the emptiest replica via the
    engine's own ``drain()`` and then ``close()``s it, so a retained
    handle that submits afterwards gets the clean ``EngineClosed``
    rejection instead of racing the teardown.  Engine-seconds are
    integrated over wall time for the engine-hours comparison the
    bench gates.
    """

    def __init__(self, factory, *, policy: AutoscalePolicy,
                 initial: int = 1, clock=None):
        import time as _time
        self._factory = factory
        self.policy = policy
        self._clock = clock or _time.monotonic
        self.replicas = []            # alive engines
        self._born = {}               # id(engine) -> birth time
        self.engine_seconds = 0.0     # integrated over retired engines
        self.scale_ups = 0
        self.scale_downs = 0
        self._busy_mark = 0.0
        self._last_decide = self._clock()
        self._last_change = -1e9
        for _ in range(max(1, int(initial))):
            self._add()

    # ----------------------------------------------------------- sizing

    def _add(self):
        eng = self._factory()
        self._born[id(eng)] = self._clock()
        self.replicas.append(eng)
        return eng

    def scale_up(self):
        """Build + warm one replica (the factory decides warmup)."""
        self.scale_ups += 1
        eng = self._add()
        self._flight("up")
        return eng

    def scale_down(self) -> bool:
        """Drain and close the emptiest replica; False at min size."""
        if len(self.replicas) <= 1:
            return False
        eng = min(self.replicas,
                  key=lambda e: (e.in_flight, len(e._pending)))
        self.replicas.remove(eng)
        eng.drain()                   # in-flight work completes first
        eng.close()                   # post-drain submits -> EngineClosed
        self.engine_seconds += self._clock() - self._born.pop(id(eng))
        self.scale_downs += 1
        self._flight("down")
        return True

    def _flight(self, action: str) -> None:
        import sys
        mod = sys.modules.get("dpf_tpu.obs.flight")
        if mod is not None:
            try:
                mod.FLIGHT.record("plan_autoscale", action=action,
                                  replicas=len(self.replicas),
                                  real=True)
            except Exception:
                pass

    # ----------------------------------------------------------- serving

    def submit(self, keys):
        """Dispatch through the least-loaded alive replica."""
        if not self.replicas:
            raise RuntimeError("replica pool is empty")
        eng = min(self.replicas,
                  key=lambda e: (e.in_flight, len(e._pending)))
        return eng.submit(keys)

    def step(self, *, slo_s: float | None = None) -> str | None:
        """One control-loop tick: sample signals, maybe scale.

        Call from the serving loop (or a timer): no-op until
        ``decide_every_s`` elapsed since the last tick.  Utilization is
        approximated by busy dispatch+wait seconds accumulated across
        replicas over the window (the same signal the twin integrates
        exactly)."""
        now = self._clock()
        dt = now - self._last_decide
        if dt < self.policy.decide_every_s:
            return None
        self._last_decide = now
        busy = sum(e.stats.dispatch_time_s + e.stats.wait_time_s
                   for e in self.replicas)
        util = max(0.0, (busy - self._busy_mark)
                   / (dt * max(1, len(self.replicas))))
        self._busy_mark = busy
        p99s = [e.stats.p99 for e in self.replicas
                if e.stats.p99 is not None]
        action = self.policy.decide(
            util=util, p99_s=max(p99s) if p99s else None, slo_s=slo_s,
            replicas=len(self.replicas),
            since_change_s=now - self._last_change)
        if action == "up":
            self.scale_up()
            self._last_change = now
        elif action == "down":
            if not self.scale_down():
                return None
            self._last_change = now
        return action

    # ---------------------------------------------------------- teardown

    def drain(self) -> None:
        for eng in self.replicas:
            eng.drain()

    def close(self) -> float:
        """Drain + close every replica; returns total engine-seconds
        (retired + still-open replicas integrated to now)."""
        now = self._clock()
        for eng in list(self.replicas):
            eng.drain()
            eng.close()
            self.engine_seconds += now - self._born.pop(id(eng))
        self.replicas = []
        return self.engine_seconds

    def stats(self) -> dict:
        return {"replicas": len(self.replicas),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "engine_seconds": round(self.engine_seconds, 4)}
