"""Digital twin of the serve stack: a seeded discrete-event simulator.

The serving tier can only *react* to load; answering "how many engines
does this tenant need to hold p99 under its SLO through tomorrow's
diurnal peak?" needs a model that replays a trace against a candidate
fleet WITHOUT standing the fleet up.  This module is that model: a
discrete-event simulation of ``serve/engine.py`` + ``serve/bench_load.
replay`` driven entirely by a serializable cost table — the same
``(construction, bucket) -> seconds`` map the router's EWMA cost model
learns (``SchemeRouter.cost_table()``) — so a twin run is a pure
function of ``(seed, trace, cost_table, fleet_config)``: bit-identical
event log and summary on every machine, **zero JAX dispatches**
(asserted in tests/test_plan.py by importing this module in a
subprocess that never loads jax).

What the twin models, mirroring the real stack piece by piece:

* the **open-loop client** of ``bench_load.replay``: arrivals released
  at their scheduled ``t`` (back-to-back when behind), a single-
  threaded poller holding at most ``window`` unresolved futures,
  per-arrival latency = resolution − *scheduled* arrival;
* the **bucket ladder** (pow2 pad + max-bucket chunking — the ~10
  lines of ``serve/buckets.py`` are reimplemented here standalone and
  parity-tested against the real class);
* **admission control** (``ServingEngine._admit``): queue-depth and
  p99-over-SLO shedding against a bounded latency ring (the real
  ring's nearest-rank quantile, parity-tested against
  ``utils/profiling.quantile``);
* ``max_in_flight`` **backpressure** per simulated device;
* **retry/backoff** (``faults.RetryPolicy``'s exact backoff formula
  with seeded jitter), per-construction **circuit breakers**
  (consecutive-failure trip, ``reset_s`` half-open re-close), and the
  supervised **rebuild delay** after an engine death;
* **faults** replayed from a ``FaultPlan`` dict
  (``FaultPlan.as_dict()``): the injector's decision function — one
  draw of ``np.random.default_rng((seed, spec_idx, arrival+1,
  consult))`` per consult, death kinds capped at one fire — is
  mirrored here exactly and parity-tested against
  ``faults.FaultInjector._decide``.

Two dispatch models, because the cost table measures a *blocking*
dispatch (``ServingEngine.probe``):

* ``dispatch_blocking=True`` (the CPU-rehearsal fidelity model): the
  dispatch call itself consumes the service time in the client thread,
  exactly like the synchronous XLA-CPU backend the committed records
  run on.  This is the configuration the ``--plan`` fidelity gate
  validates against the real harness.
* ``dispatch_blocking=False`` (the fleet model): dispatch is an async
  enqueue onto a per-replica serial device queue; replicas drain in
  parallel, ``max_in_flight`` bounds the per-replica window.  This is
  the model the capacity planner and autoscaler sweep, where multiple
  replicas must actually overlap.

This module (and the rest of ``dpf_tpu/plan``'s pure core) imports
ONLY the stdlib and numpy — never jax, never another dpf_tpu package —
so the reproducibility claim is structural, not best-effort.  Flight
events are emitted only when ``dpf_tpu.obs.flight`` is ALREADY loaded
(the twin never triggers the package import itself).
"""

from __future__ import annotations

import dataclasses
import heapq  # noqa: F401  (re-exported for planners building event heaps)
import sys
from collections import deque

import numpy as np

#: bounded size of the simulated latency ring — MUST equal
#: utils.profiling.LATENCY_RING (parity-tested) so the twin's p99 shed
#: trigger sees the same window the real engine does
LATENCY_RING = 2048

#: fault kinds the twin replays with timing effect; the remaining real
#: kinds (corrupt_shares, compile_error) are correctness/warmup faults
#: with no steady-state timing signature, so the twin only counts them
TIMED_FAULT_KINDS = ("dispatch_error", "latency", "engine_death",
                     "host_drop")


def _flight(kind: str, **attrs) -> None:
    """Record a flight event IF the flight recorder is already loaded.

    The twin must never import dpf_tpu.obs itself (the package root
    pulls jax); when a bench/planner process already has it, twin runs
    show up on the same timeline as the real serving events."""
    mod = sys.modules.get("dpf_tpu.obs.flight")
    if mod is not None:
        try:
            mod.FLIGHT.record(kind, **attrs)
        except Exception:
            pass


def quantile(samples, q: float) -> float:
    """Nearest-rank quantile — the exact formula of
    ``utils/profiling.quantile`` (parity-tested), reimplemented so the
    twin's SLO math is the engine's SLO math without importing the
    jax-adjacent utils package."""
    if not samples:
        raise ValueError("quantile of an empty sample set")
    s = sorted(samples)
    return s[min(len(s) - 1, max(0, int(q * len(s) + 0.5) - 1))]


# ----------------------------------------------------------- cost table


class CostTable:
    """Serializable ``(construction, bucket) -> seconds`` service times.

    The twin's only notion of "how fast is the hardware": one blocking-
    dispatch cost per (construction, bucket), exactly what
    ``SchemeRouter.cost_table()`` exports from its live EWMA model (or
    ``tune.serve_tune.cached_cost_table`` recovers from the tuning
    cache).  Keys serialize as ``"label@bucket"`` — the same spelling
    ``SchemeRouter.stats()["cost_model_ms"]`` uses — so a table embedded
    in a benchmark record is directly auditable against the router's.

    A bucket with no exact entry is estimated from the nearest measured
    bucket of the same construction, scaled linearly by size (bucket
    cost is dominated by the padded batch's device work).
    """

    def __init__(self, costs, overhead_s: float = 0.0):
        self._costs = {}
        for key, s in dict(costs).items():
            if isinstance(key, str):
                lb, bk = key.rsplit("@", 1)
                key = (lb, int(bk))
            self._costs[(str(key[0]), int(key[1]))] = float(s)
        if not self._costs:
            raise ValueError("cost table is empty")
        #: fixed per-batch host overhead (decode/pack), added once per
        #: submitted batch on top of the per-chunk device costs
        self.overhead_s = float(overhead_s)

    def labels(self) -> tuple:
        return tuple(sorted({lb for lb, _ in self._costs}))

    def buckets(self, label: str) -> tuple:
        return tuple(sorted(bk for lb, bk in self._costs if lb == label))

    def service_s(self, label: str, bucket: int) -> float:
        """Service seconds for one blocking dispatch at ``bucket``."""
        hit = self._costs.get((label, bucket))
        if hit is not None:
            return hit
        measured = self.buckets(label)
        if not measured:
            raise KeyError("no costs for construction %r" % (label,))
        nearest = min(measured, key=lambda b: abs(b - bucket))
        return self._costs[(label, nearest)] * (bucket / nearest)

    def as_dict(self) -> dict:
        d = {"%s@%d" % k: v for k, v in sorted(self._costs.items())}
        if self.overhead_s:
            d["overhead_s"] = self.overhead_s
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CostTable":
        d = dict(d)
        overhead = float(d.pop("overhead_s", 0.0))
        return cls(d, overhead_s=overhead)

    def __repr__(self):
        return "CostTable(%d entries, labels=%s)" % (
            len(self._costs), list(self.labels()))


# ---------------------------------------------------------- fleet config


@dataclasses.dataclass
class FleetConfig:
    """One candidate fleet, fully serializable (twin inputs must be
    auditable from a committed record).

    ``replicas`` maps construction label -> engine-replica count.
    ``bucket_sizes`` is the shared ladder (pow2, like
    ``serve/buckets.py``); ``window`` is the open-loop client's
    unresolved-future bound (``bench_load.replay``'s knob, NOT an
    engine knob).  ``rebuild_s`` is the supervised-rebuild delay after
    an injected engine death (None = dead engines stay dead);
    ``spinup_s`` is the warmup delay before a scaled-up replica takes
    traffic.  ``host_slots`` converts engines to hosts for the
    capacity planner (engines per host)."""
    replicas: dict
    bucket_sizes: tuple = (64, 128, 256, 512)
    max_in_flight: int = 2
    window: int = 8
    max_queue_depth: int | None = None
    slo_s: float | None = None
    shed: bool = False
    dispatch_blocking: bool = True
    retry_max_attempts: int = 3
    retry_backoff_s: float = 0.005
    retry_backoff_mult: float = 2.0
    retry_jitter: float = 0.5
    breaker_failures: int = 5
    breaker_reset_s: float = 30.0
    rebuild_s: float | None = None
    spinup_s: float = 0.2
    host_slots: int = 4
    # ---- HBM paging (the big-table tier): when a replica's share of
    # the table exceeds its device budget, every full-domain dispatch
    # must page the missing bytes host->device; the stall per dispatch
    # is missing_bytes / page_gbps, discounted by prefetch_overlap
    # (the fraction the GranulePrefetcher hides behind in-flight
    # compute).  table_bytes=0 or hbm_bytes_per_replica=None = no
    # paging modeled (the pre-bigtable behavior, field-for-field).
    table_bytes: int = 0
    hbm_bytes_per_replica: int | None = None
    page_gbps: float = 8.0
    prefetch_overlap: float = 0.0

    def __post_init__(self):
        self.replicas = {str(k): int(v)
                         for k, v in dict(self.replicas).items()}
        sizes = sorted({int(s) for s in self.bucket_sizes})
        for s in sizes:
            if s < 1 or (s & (s - 1)) != 0:
                raise ValueError("bucket sizes must be powers of two "
                                 ">= 1 (got %r)" % (s,))
        self.bucket_sizes = tuple(sizes)
        if self.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if self.window < 1:
            raise ValueError("window must be >= 1")
        if self.table_bytes < 0:
            raise ValueError("table_bytes must be >= 0")
        if self.page_gbps <= 0:
            raise ValueError("page_gbps must be > 0")
        if not 0 <= self.prefetch_overlap <= 1:
            raise ValueError("prefetch_overlap must be in [0, 1] "
                             "(got %r)" % (self.prefetch_overlap,))

    # -- the ~10 lines of serve/buckets.py the twin needs, standalone
    #    (parity-tested against the real Buckets in tests/test_plan.py)

    @property
    def max_bucket(self) -> int:
        return self.bucket_sizes[-1]

    def bucket_for(self, b: int) -> int:
        """Smallest bucket >= b (``Buckets.bucket_for``)."""
        if b < 1:
            raise ValueError("batch must be >= 1 (got %d)" % b)
        for s in self.bucket_sizes:
            if s >= b:
                return s
        raise ValueError("batch %d exceeds the largest bucket %d"
                         % (b, self.max_bucket))

    def chunks(self, b: int) -> list:
        """Max-bucket spans + remainder (``Buckets.chunks``)."""
        if b < 1:
            raise ValueError("batch must be >= 1 (got %d)" % b)
        spans, lo = [], 0
        while b - lo > self.max_bucket:
            spans.append((lo, lo + self.max_bucket))
            lo += self.max_bucket
        spans.append((lo, b))
        return spans

    def paging_stall_s(self) -> float:
        """Host->device paging stall per full-domain dispatch (0.0
        when paging is not modeled).  A full-domain eval touches every
        table row, so the bytes that don't fit in the replica's device
        budget must stream in on EVERY dispatch:
        ``missing / page_gbps``, discounted by ``prefetch_overlap``."""
        if self.table_bytes == 0 or self.hbm_bytes_per_replica is None:
            return 0.0
        missing = max(0, self.table_bytes - self.hbm_bytes_per_replica)
        return (missing / (self.page_gbps * (1 << 30))
                * (1.0 - self.prefetch_overlap))

    def total_replicas(self) -> int:
        return sum(self.replicas.values())

    def hosts(self) -> int:
        """Hosts needed at ``host_slots`` engines per host."""
        return -(-self.total_replicas() // self.host_slots)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["bucket_sizes"] = list(self.bucket_sizes)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FleetConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


# ------------------------------------------------------------ fault mirror


class FaultMirror:
    """The FaultInjector decision function, replayed from a plan dict.

    Mirrors ``serve/faults.FaultInjector`` exactly for the decision
    math (parity-tested in tests/test_plan.py): each consult draws from
    ``np.random.default_rng((seed, spec_idx, arrival + 1, consult))``,
    ``p >= 1.0`` short-circuits the draw, death kinds
    (engine_death/host_drop) fire at most once, ``max_fires`` bounds
    the rest.  Takes ``FaultPlan.as_dict()`` — a plain dict — so this
    module never imports the jax-importing serve package."""

    _DEFAULTS = dict(construction=None, bucket=None, start=0, stop=None,
                     p=1.0, latency_s=0.05, max_fires=None)

    def __init__(self, plan: dict | None):
        plan = plan or {}
        self.seed = int(plan.get("seed", 0))
        self.specs = [dict(self._DEFAULTS, **s)
                      for s in plan.get("specs", ())]
        self.arrival = -1
        self.injected = {}
        self._consults = {}           # (spec_idx, arrival) -> count
        self._fires = {}              # spec_idx -> total fires

    def begin_arrival(self, j: int) -> None:
        self.arrival = int(j)

    def _matches(self, spec: dict, label, bucket) -> bool:
        if (spec["construction"] is not None
                and label != spec["construction"]):
            return False
        if spec["bucket"] is not None and bucket != spec["bucket"]:
            return False
        if self.arrival < spec["start"]:
            return False
        return spec["stop"] is None or self.arrival < spec["stop"]

    def _fires_left(self, idx: int, spec: dict) -> bool:
        cap = (1 if spec["kind"] in ("engine_death", "host_drop")
               else spec["max_fires"])
        return cap is None or self._fires.get(idx, 0) < cap

    def _decide(self, idx: int, spec: dict) -> bool:
        key = (idx, self.arrival)
        consult = self._consults.get(key, 0)
        self._consults[key] = consult + 1
        if spec["p"] >= 1.0:
            fired = True
        else:
            rng = np.random.default_rng(
                (self.seed, idx, self.arrival + 1, consult))
            fired = bool(rng.random() < spec["p"])
        if fired:
            if not self._fires_left(idx, spec):
                return False
            self._fires[idx] = self._fires.get(idx, 0) + 1
            self.injected[spec["kind"]] = (
                self.injected.get(spec["kind"], 0) + 1)
        return fired

    def firing(self, kinds, label, bucket) -> list:
        """Specs of ``kinds`` firing at the current (label, bucket,
        arrival) — the twin's ``_firing``, eagerly materialized."""
        out = []
        for idx, spec in enumerate(self.specs):
            if (spec["kind"] in kinds and self._fires_left(idx, spec)
                    and self._matches(spec, label, bucket)
                    and self._decide(idx, spec)):
                out.append(spec)
        return out


# ----------------------------------------------------------- sim pieces


class _SimBreaker:
    """CircuitBreaker over virtual time (same closed/open/half_open
    machine as ``faults.CircuitBreaker``, ``time.monotonic`` replaced
    by the sim clock)."""

    __slots__ = ("failures", "reset_s", "state", "consecutive",
                 "opened_at", "opens")

    def __init__(self, failures: int, reset_s: float):
        self.failures = int(failures)
        self.reset_s = float(reset_s)
        self.state = "closed"
        self.consecutive = 0
        self.opened_at = None
        self.opens = 0

    def record_failure(self, now: float) -> None:
        self.consecutive += 1
        if self.state == "half_open":
            self.state, self.opened_at = "open", now
        elif (self.state == "closed"
              and self.consecutive >= self.failures):
            self.state, self.opened_at = "open", now
            self.opens += 1
        elif self.state == "open":
            self.opened_at = now

    def record_success(self) -> None:
        self.consecutive = 0
        self.state = "closed"

    def available(self, now: float) -> bool:
        if (self.state == "open" and self.opened_at is not None
                and now - self.opened_at >= self.reset_s):
            self.state = "half_open"   # re-probe is free in the twin:
            #                            the next success re-closes it
        return self.state in ("closed", "half_open")


class _SimReplica:
    """One simulated engine replica: a serial device queue plus the
    liveness/accounting the fleet model needs."""

    __slots__ = ("label", "rid", "free_t", "inflight", "alive",
                 "draining", "rebuild_at", "busy_s", "alive_spans")

    def __init__(self, label: str, rid: int, born_t: float):
        self.label = label
        self.rid = rid
        self.free_t = born_t        # device available from here
        self.inflight = deque()     # unresolved chunk completion times
        self.alive = True
        self.draining = False
        self.rebuild_at = None
        self.busy_s = 0.0
        self.alive_spans = [[born_t, None]]   # engine-hours integral

    def kill(self, now: float, rebuild_s: float | None) -> None:
        self.alive = False
        self.inflight.clear()
        if self.alive_spans and self.alive_spans[-1][1] is None:
            self.alive_spans[-1][1] = now
        self.rebuild_at = (None if rebuild_s is None
                           else now + rebuild_s)

    def revive(self, now: float) -> None:
        self.alive = True
        self.rebuild_at = None
        self.free_t = max(self.free_t, now)
        self.alive_spans.append([now, None])

    def retire(self, now: float) -> None:
        """Scale-down drain: stop taking work; engine-hours run until
        the queue empties (``free_t``)."""
        self.draining = True
        if self.alive_spans and self.alive_spans[-1][1] is None:
            self.alive_spans[-1][1] = max(now, self.free_t)
        self.alive = False

    def engine_seconds(self, end_t: float) -> float:
        total = 0.0
        for a, b in self.alive_spans:
            total += (end_t if b is None else min(b, end_t)) - a
        return max(0.0, total)


class _Ring:
    """The engine's bounded latency ring (LATENCY_RING samples,
    circular overwrite) — the p99 source of the shed trigger."""

    __slots__ = ("samples", "pos")

    def __init__(self):
        self.samples = []
        self.pos = 0

    def note(self, s: float) -> None:
        if len(self.samples) < LATENCY_RING:
            self.samples.append(s)
        else:
            self.samples[self.pos] = s
            self.pos = (self.pos + 1) % LATENCY_RING

    def p99(self) -> float | None:
        if not self.samples:
            return None
        return quantile(self.samples, 0.99)


class PlannerStats:
    """Process-wide planning counters, exported as ``dpf_plan_*``
    metrics by ``obs.metrics.register_planner`` (the bench registers
    the module singleton ``PLAN_STATS``)."""

    def __init__(self):
        self.twin_runs = 0
        self.sim_arrivals = 0
        self.sim_sheds = 0
        self.sweeps = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_p99_ms = None
        self.last_replicas = None


#: the singleton obs.metrics watches (module-owned, so the weakref
#: registration idiom keeps it alive for the process lifetime)
PLAN_STATS = PlannerStats()


# ------------------------------------------------------------ the twin


class TwinResult:
    """One twin run: the full event log plus derived summary stats.

    ``events`` is a list of plain dicts in simulation order — the
    bit-reproducibility surface (same inputs, identical list).
    ``summary()`` derives the SLO/availability/engine-hours record the
    planner and the fidelity gate consume."""

    def __init__(self, events, lats, ring, served, sheds, shed_q,
                 failed, makespan_s, total_q, route_counts, injected,
                 replicas, fleet, autoscale_log):
        self.events = events
        self.lats = lats
        self._ring = ring
        self.served = served
        self.sheds = sheds
        self.shed_queries = shed_q
        self.failed = failed
        self.makespan_s = makespan_s
        self.total_queries = total_q
        self.route_counts = route_counts
        self.injected = injected
        self._replicas = replicas
        self._fleet = fleet
        self.autoscale_log = autoscale_log

    def p(self, q: float) -> float | None:
        return quantile(self.lats, q) if self.lats else None

    def engine_hours(self) -> float:
        end = self.makespan_s
        return sum(r.engine_seconds(end)
                   for r in self._replicas) / 3600.0

    def summary(self) -> dict:
        n_ans = self.served + self.failed
        lat_ms = {
            "p50_ms": None, "p95_ms": None, "p99_ms": None,
            "max_ms": None}
        if self.lats:
            ms = sorted(x * 1e3 for x in self.lats)
            lat_ms = {
                "p50_ms": round(quantile(ms, 0.50), 3),
                "p95_ms": round(quantile(ms, 0.95), 3),
                "p99_ms": round(quantile(ms, 0.99), 3),
                "max_ms": round(ms[-1], 3)}
        offered = self.served + self.failed + self.sheds
        return {
            "arrivals": offered,
            "served": self.served,
            "failed": self.failed,
            "shed_batches": self.sheds,
            "shed_queries": self.shed_queries,
            "shed_rate": (round(self.sheds / offered, 4)
                          if offered else 0.0),
            "availability": (round(self.served / n_ans, 4)
                             if n_ans else 1.0),
            "makespan_s": round(self.makespan_s, 4),
            "qps": (int((self.total_queries - self.shed_queries)
                        / self.makespan_s)
                    if self.makespan_s > 0 else 0),
            **lat_ms,
            "engine_hours": round(self.engine_hours(), 6),
            "route_counts": dict(self.route_counts),
            "faults_injected": dict(self.injected),
            "replicas_final": {
                lb: sum(1 for r in self._replicas
                        if r.label == lb and r.alive)
                for lb in self._fleet.replicas},
            "autoscale": {
                "ups": sum(1 for e in self.autoscale_log
                           if e["action"] == "up"),
                "downs": sum(1 for e in self.autoscale_log
                             if e["action"] == "down"),
                "log": list(self.autoscale_log)},
        }


def _as_arrivals(trace) -> list:
    """Normalize a trace into [(t, batch)] — accepts ``loadgen.
    Arrival`` duck-types, (t, batch) pairs, or {"t": .., "batch": ..}
    dicts (the serialized spelling a record embeds)."""
    out = []
    for a in trace:
        if hasattr(a, "t") and hasattr(a, "batch"):
            out.append((float(a.t), int(a.batch)))
        elif isinstance(a, dict):
            out.append((float(a["t"]), int(a["batch"])))
        else:
            t, b = a
            out.append((float(t), int(b)))
    return out


def simulate(trace, cost_table, fleet, *, seed: int = 0,
             fault_plan: dict | None = None, autoscaler=None,
             record_events: bool = True) -> TwinResult:
    """Run the digital twin: replay ``trace`` against ``fleet`` with
    service times from ``cost_table``.

    Pure function of ``(seed, trace, cost_table, fleet, fault_plan,
    autoscaler)``: no wall clock, no global state, every random draw
    seeded — two calls with equal inputs return identical ``events``
    lists and summaries.  ``fault_plan`` is a ``FaultPlan.as_dict()``
    dict; ``autoscaler`` an ``autoscale.AutoscalePolicy`` (or any
    object with its ``decide``/``decide_every_s`` surface) evaluated
    over virtual time.
    """
    if isinstance(cost_table, dict):
        cost_table = CostTable.from_dict(cost_table)
    arrivals = _as_arrivals(trace)
    injector = FaultMirror(fault_plan)
    retry_rng = np.random.default_rng((int(seed), 0x5e77))
    events = []

    def ev(_k, **attrs):
        if record_events:
            events.append({"k": _k, **attrs})

    # ---- fleet state -------------------------------------------------
    replicas = []
    for lb, count in sorted(fleet.replicas.items()):
        for i in range(count):
            replicas.append(_SimReplica(lb, len(replicas), 0.0))
    breakers = {lb: _SimBreaker(fleet.breaker_failures,
                                fleet.breaker_reset_s)
                for lb in fleet.replicas}
    ring = _Ring()
    outstanding = deque()       # (submit_t, sched_t, completion_t)
    lats = []
    served = failed = sheds = shed_q = 0
    route_counts = {lb: 0 for lb in fleet.replicas}
    autoscale_log = []
    as_state = {"last_decide": 0.0, "last_change": -1e9,
                "busy_mark": 0.0, "next_rid": len(replicas)}

    def total_busy():
        return sum(r.busy_s for r in replicas)

    def alive_of(lb):
        return [r for r in replicas if r.label == lb and r.alive]

    def revive_due(now):
        for r in replicas:
            if (not r.alive and not r.draining
                    and r.rebuild_at is not None
                    and now >= r.rebuild_at):
                r.revive(now)
                ev("rebuild", t=now, label=r.label, rid=r.rid)

    def backoff_s(attempt):
        # RetryPolicy.backoff with the policy's seeded-jitter shape;
        # the twin uses its own seeded stream (the real policy's rng
        # order depends on wall-clock thread interleaving)
        base = (fleet.retry_backoff_s
                * fleet.retry_backoff_mult ** max(0, attempt - 1))
        return base * (1.0 + fleet.retry_jitter
                       * float(retry_rng.random()))

    def maybe_autoscale(now):
        if autoscaler is None:
            return
        if now - as_state["last_decide"] < autoscaler.decide_every_s:
            return
        dt = now - as_state["last_decide"]
        as_state["last_decide"] = now
        n_alive = sum(1 for r in replicas if r.alive)
        busy = total_busy()
        util = ((busy - as_state["busy_mark"]) / (dt * n_alive)
                if n_alive and dt > 0 else 0.0)
        as_state["busy_mark"] = busy
        action = autoscaler.decide(
            util=util, p99_s=ring.p99(), slo_s=fleet.slo_s,
            replicas=n_alive,
            since_change_s=now - as_state["last_change"])
        if action is None:
            return
        if action == "up":
            # replicate the construction with the most traffic so far
            lb = max(route_counts, key=lambda l: (route_counts[l], l))
            r = _SimReplica(lb, as_state["next_rid"], now)
            r.free_t = now + fleet.spinup_s
            as_state["next_rid"] += 1
            replicas.append(r)
            PLAN_STATS.scale_ups += 1
        else:
            # retire the emptiest alive replica, respecting min bound
            cands = [r for r in replicas if r.alive]
            if len(cands) <= 1:
                return
            r = min(cands, key=lambda x: (x.free_t, x.rid))
            r.retire(now)
            PLAN_STATS.scale_downs += 1
        as_state["last_change"] = now
        entry = {"t": round(now, 6), "action": action,
                 "label": r.label, "rid": r.rid,
                 "replicas": sum(1 for x in replicas if x.alive),
                 "util": round(util, 4)}
        autoscale_log.append(entry)
        ev("autoscale", **entry)
        _flight("plan_autoscale", **entry)

    # ---- the open-loop client (bench_load.replay over virtual time) --
    now = 0.0

    def resolve_oldest():
        nonlocal now
        sub_t, sched_t, comp_t = outstanding.popleft()
        now = max(now, comp_t)
        lats.append(now - sched_t)
        ring.note(now - sub_t)

    for j, (at, batch) in enumerate(arrivals):
        while now < at:
            if outstanding:
                resolve_oldest()
            else:
                now = at
        while len(outstanding) >= fleet.window:
            resolve_oldest()
        revive_due(now)
        maybe_autoscale(now)
        injector.begin_arrival(j)
        PLAN_STATS.sim_arrivals += 1
        submit_t = now

        # ---- admission control (ServingEngine._admit) ----------------
        over_depth = (fleet.max_queue_depth is not None
                      and len(outstanding) >= fleet.max_queue_depth)
        over_slo = False
        if fleet.slo_s is not None and outstanding:
            p99 = ring.p99()
            over_slo = p99 is not None and p99 > fleet.slo_s
        if fleet.shed and (over_depth or over_slo):
            sheds += 1
            shed_q += batch
            PLAN_STATS.sim_sheds += 1
            ev("shed", j=j, t=now, batch=batch,
               reason="queue_depth" if over_depth else "p99_over_slo")
            continue
        while (fleet.max_queue_depth is not None
               and len(outstanding) >= fleet.max_queue_depth):
            resolve_oldest()

        # ---- route + dispatch with retry/failover --------------------
        attempt = 0
        excluded = set()
        comp_t = None
        while True:
            attempt += 1
            avail = [lb for lb in sorted(fleet.replicas)
                     if lb not in excluded and alive_of(lb)
                     and breakers[lb].available(now)]
            if not avail:
                avail = [lb for lb in sorted(fleet.replicas)
                         if lb not in excluded and alive_of(lb)]
            if not avail:
                failed += 1
                ev("fail", j=j, t=now, batch=batch,
                   reason="no_alive_replica")
                break
            bucket0 = fleet.bucket_for(min(batch, fleet.max_bucket))
            label = min(avail,
                        key=lambda lb: cost_table.service_s(lb,
                                                            bucket0))
            rep = min(alive_of(label), key=lambda r: (r.free_t, r.rid))
            try:
                comp_t, now = _dispatch(rep, batch, fleet, cost_table,
                                        injector, label, now)
            except _SimFault as f:
                now = f.now
                breakers[label].record_failure(now)
                if f.kind in ("engine_death", "host_drop"):
                    rep.kill(now, fleet.rebuild_s)
                    ev("death", j=j, t=now, label=label, rid=rep.rid,
                       kind=f.kind)
                    if not alive_of(label):
                        excluded.add(label)
                if attempt >= fleet.retry_max_attempts:
                    failed += 1
                    ev("fail", j=j, t=now, batch=batch,
                       reason=f.kind, attempts=attempt)
                    break
                if f.kind not in ("engine_death", "host_drop"):
                    now += backoff_s(attempt)
                ev("retry", j=j, t=now, label=label, attempt=attempt,
                   reason=f.kind)
                continue
            breakers[label].record_success()
            route_counts[label] = route_counts.get(label, 0) + 1
            served += 1
            outstanding.append((submit_t, at, comp_t))
            ev("serve", j=j, t=now, label=label, rid=rep.rid,
               batch=batch, comp=comp_t, attempt=attempt)
            break

    while outstanding:
        resolve_oldest()

    makespan = now if arrivals else 0.0
    total_q = sum(b for _, b in arrivals)
    PLAN_STATS.twin_runs += 1
    result = TwinResult(events, lats, ring, served, sheds, shed_q,
                        failed, makespan, total_q, route_counts,
                        injector.injected, replicas, fleet,
                        autoscale_log)
    if lats:
        PLAN_STATS.last_p99_ms = round(quantile(lats, 0.99) * 1e3, 3)
    PLAN_STATS.last_replicas = sum(1 for r in replicas if r.alive)
    _flight("plan_twin", arrivals=len(arrivals), served=served,
            sheds=sheds, failed=failed,
            p99_ms=PLAN_STATS.last_p99_ms)
    return result


class _SimFault(Exception):
    """An injected fault inside a simulated dispatch; carries the sim
    clock at the moment of failure."""

    def __init__(self, kind: str, now: float):
        super().__init__(kind)
        self.kind = kind
        self.now = now


def _dispatch(rep: _SimReplica, batch: int, fleet: FleetConfig,
              cost: CostTable, injector: FaultMirror, label: str,
              now: float) -> tuple:
    """Simulate one ``ServingEngine.submit``: chunk, pad, consult the
    injector at the per-chunk dispatch point, and advance time.

    Returns ``(completion_t, new_now)``.  Raises ``_SimFault`` on an
    injected failure — the caller unwinds exactly like the real
    partial-unwind (the simulated device has no orphaned state to
    clean up)."""
    now += cost.overhead_s
    comp = now
    for lo, hi in fleet.chunks(batch):
        size = fleet.bucket_for(hi - lo)
        # injection points, in FaultInjector.on_dispatch's kind order
        deaths = injector.firing(("engine_death", "host_drop"), label,
                                 size)
        if deaths:
            raise _SimFault(deaths[0]["kind"], now)
        extra = sum(s["latency_s"] for s in
                    injector.firing(("latency",), label, size))
        if injector.firing(("dispatch_error",), label, size):
            raise _SimFault("dispatch_error", now + extra)
        svc = cost.service_s(label, size) + extra + fleet.paging_stall_s()
        rep.busy_s += svc
        if fleet.dispatch_blocking:
            # CPU model: the dispatch call computes synchronously in
            # the client thread (what ServingEngine.probe measured)
            now += svc
            comp = now
            rep.free_t = max(rep.free_t, now)
        else:
            # TPU model: async enqueue onto the replica's serial
            # device queue, max_in_flight bounding the window
            while len(rep.inflight) >= fleet.max_in_flight:
                now = max(now, rep.inflight.popleft())
            start = max(now, rep.free_t)
            done = start + svc
            rep.free_t = done
            rep.inflight.append(done)
            comp = done
    return comp, now
