"""Capacity planning: the digital twin, the fleet planner, autoscaling.

``dpf_tpu.plan`` answers fleet-sizing questions without standing a
fleet up: a seeded discrete-event twin of the serve stack
(``twin.py``), a replica-sweep capacity planner (``capacity.py``), and
a reactive autoscale policy evaluated in the twin AND runnable against
real engines (``autoscale.py``).  ``bench_plan.py`` is the
``benchmark.py --plan`` entry whose headline gate is twin fidelity
against the real open-loop harness; docs/PLANNING.md is the guide.

The pure core (twin/capacity/autoscale) imports only stdlib+numpy —
no jax, no other dpf_tpu packages — so a twin run is reproducible with
zero JAX dispatches (tests/test_plan.py asserts this by importing the
modules in a jax-free subprocess).  Import them via this package in
normal code; the subprocess trick exists only to PROVE the property.
"""

from .autoscale import AutoscalePolicy, ReplicaPool
from .capacity import plan_fleet, required_replicas
from .twin import (CostTable, FaultMirror, FleetConfig, PLAN_STATS,
                   TwinResult, simulate)

__all__ = [
    "AutoscalePolicy", "CostTable", "FaultMirror", "FleetConfig",
    "PLAN_STATS", "ReplicaPool", "TwinResult", "plan_fleet",
    "required_replicas", "simulate",
]
