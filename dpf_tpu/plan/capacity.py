"""Fleet capacity planner: minimal engines/hosts meeting an SLO.

Given a trace, an SLO, and a fingerprint's cost table
(``SchemeRouter.cost_table()`` live, or ``tune.serve_tune.
cached_cost_table`` from the tuning cache), sweep replica counts
through the digital twin (``plan/twin.py``) and report the smallest
fleet that holds p99 under the SLO with an acceptable shed rate —
plus headroom curves (required replicas at scaled offered loads, via
``loadgen.scale_rate``-style time compression applied here to keep the
module jax-free).

Planner invariants (gated in the ``--plan`` record):

* **monotone in offered load** — more qps never plans fewer engines.
  The sweep enforces this by construction (a running max over
  ascending load scales), so a non-monotone twin artifact can never
  leak into a sizing decision.
* hosts = ceil(engines / host_slots) (``FleetConfig.hosts``).

Pure stdlib+numpy, like the twin: the planner runs with zero JAX
dispatches.
"""

from __future__ import annotations

import dataclasses

from .twin import CostTable, FleetConfig, PLAN_STATS, simulate


def _scale_trace(trace, factor: float) -> list:
    """Compress arrival times by ``factor`` (> 1 = hotter), keeping
    batches — the twin-side equivalent of ``loadgen.scale_rate``
    (kept here, duplicated in spirit, so the planner never imports the
    jax-adjacent serve package)."""
    if factor <= 0:
        raise ValueError("factor must be > 0 (got %r)" % (factor,))
    out = []
    for a in trace:
        if hasattr(a, "t"):
            out.append((float(a.t) / factor, int(a.batch)))
        elif isinstance(a, dict):
            out.append((float(a["t"]) / factor, int(a["batch"])))
        else:
            t, b = a
            out.append((float(t) / factor, int(b)))
    return out


#: fallback per-host HBM byte budget when neither the caller nor the
#: device probe supplies one (a mid-range accelerator host; the point
#: of the default is a usable memory floor, not precision — real plans
#: pass the probed or provisioned figure)
DEFAULT_HBM_BYTES = 16 << 30


def detect_hbm_budget(device=None) -> int | None:
    """Per-host HBM byte budget probed from the local device
    (``utils.compat.device_memory_stats`` -> ``bytes_limit``); None on
    CPU/old-jax hosts, where there is no device ceiling to plan
    around.  The only jax-adjacent call in the plan package — and it
    stays import-lazy and failure-proof, so the planner itself remains
    runnable with zero JAX dispatches."""
    try:
        from ..utils.compat import device_memory_stats
        st = device_memory_stats(device)
    except Exception:
        return None
    if not st:
        return None
    limit = st.get("bytes_limit") or st.get("bytes_reservable_limit")
    return int(limit) if limit else None


def min_hosts_for_memory(table_bytes: int,
                         hbm_bytes_per_host: int) -> int:
    """The memory floor: hosts needed just to HOLD ``table_bytes`` of
    table at ``hbm_bytes_per_host`` each (the 2D/cluster tiers shard
    the table across hosts, so fleet HBM is hosts x per-host budget).
    Monotone in table bytes by construction (a ceil of a ratio)."""
    if table_bytes < 0:
        raise ValueError("table_bytes must be >= 0")
    if hbm_bytes_per_host < 1:
        raise ValueError("hbm_bytes_per_host must be >= 1")
    return max(1, -(-int(table_bytes) // int(hbm_bytes_per_host)))


@dataclasses.dataclass
class PlanResult:
    """One planned point: the minimal passing fleet and its twin run."""
    replicas: int
    hosts: int
    met_slo: bool
    summary: dict

    def as_dict(self) -> dict:
        return {"replicas": self.replicas, "hosts": self.hosts,
                "met_slo": self.met_slo, "summary": self.summary}


def required_replicas(trace, cost_table, *, label: str, slo_s: float,
                      fleet_kw: dict | None = None, seed: int = 0,
                      max_replicas: int = 16,
                      max_shed_rate: float = 0.0,
                      dispatch_blocking: bool = False) -> PlanResult:
    """Smallest replica count of ``label`` whose twin run meets the
    SLO (p99 <= slo_s and shed_rate <= max_shed_rate and no failed
    arrivals) on ``trace``.

    Sweeps 1..max_replicas ascending and stops at the first pass; when
    nothing passes, returns the ``max_replicas`` run with
    ``met_slo=False`` (the caller sees the planner saturated rather
    than a silent cap).  Uses the fleet (async-dispatch) twin model by
    default — replicas must overlap to matter.
    """
    if isinstance(cost_table, dict):
        cost_table = CostTable.from_dict(cost_table)
    fleet_kw = dict(fleet_kw or {})
    fleet_kw.setdefault("slo_s", slo_s)
    last = None
    for r in range(1, max_replicas + 1):
        fleet = FleetConfig(replicas={label: r},
                            dispatch_blocking=dispatch_blocking,
                            **fleet_kw)
        res = simulate(trace, cost_table, fleet, seed=seed,
                       record_events=False)
        PLAN_STATS.sweeps += 1
        s = res.summary()
        p99 = s["p99_ms"]
        ok = (p99 is not None and p99 <= slo_s * 1e3
              and s["shed_rate"] <= max_shed_rate
              and s["failed"] == 0)
        last = PlanResult(replicas=r, hosts=fleet.hosts(),
                          met_slo=ok, summary=s)
        if ok:
            return last
    return last


def plan_fleet(trace, cost_table, *, label: str, slo_s: float,
               load_scales=(0.5, 1.0, 1.5, 2.0), seed: int = 0,
               fleet_kw: dict | None = None, max_replicas: int = 16,
               max_shed_rate: float = 0.0, host_slots: int = 4,
               table_bytes: int | None = None,
               hbm_bytes_per_host: int | None = None) -> dict:
    """The capacity plan: minimal fleet at the offered load plus the
    headroom curve over ``load_scales``.

    Monotonicity is enforced by construction: replicas at each scale
    are the running max over ascending scales, so "more qps never
    plans fewer engines" holds for every emitted plan — any twin
    noise that would dip the curve is absorbed upward (conservative:
    over-provisioning, never under).

    ``table_bytes`` makes HBM a first-class resource next to compute:
    every curve point's ``hosts`` becomes ``max(throughput hosts,
    memory-floor hosts)`` where the floor is
    ``min_hosts_for_memory(table_bytes, hbm_bytes_per_host)`` — the
    hosts needed just to HOLD the sharded table.  This answers "how
    many hosts for a 10^9-row table at this qps" with a curve that is
    JOINTLY monotone: nondecreasing in offered load (running max) and
    nondecreasing in table bytes (a ceil of a ratio), because a max of
    monotone terms is monotone.  ``hbm_bytes_per_host`` resolves
    explicit > device probe (``detect_hbm_budget``) >
    ``DEFAULT_HBM_BYTES``, with the provenance recorded."""
    if isinstance(cost_table, dict):
        cost_table = CostTable.from_dict(cost_table)
    fleet_kw = dict(fleet_kw or {})
    fleet_kw.setdefault("host_slots", host_slots)
    memory = None
    mem_hosts = 0
    if table_bytes is not None:
        if hbm_bytes_per_host is not None:
            hbm, hbm_source = int(hbm_bytes_per_host), "explicit"
        else:
            hbm = detect_hbm_budget()
            if hbm is not None:
                hbm_source = "device"
            else:
                hbm, hbm_source = DEFAULT_HBM_BYTES, "default"
        mem_hosts = min_hosts_for_memory(table_bytes, hbm)
        memory = {"table_bytes": int(table_bytes),
                  "hbm_bytes_per_host": hbm,
                  "hbm_source": hbm_source,
                  "hosts_memory_floor": mem_hosts}
    scales = sorted(set(float(s) for s in load_scales) | {1.0})
    curve = []
    running = 0
    for sc in scales:
        scaled = _scale_trace(trace, sc)
        pr = required_replicas(
            scaled, cost_table, label=label, slo_s=slo_s,
            fleet_kw=fleet_kw, seed=seed, max_replicas=max_replicas,
            max_shed_rate=max_shed_rate)
        planned = max(running, pr.replicas)
        running = planned
        hosts_tp = -(-planned // int(fleet_kw["host_slots"]))
        curve.append({
            "load_scale": sc,
            "replicas": planned,
            "replicas_raw": pr.replicas,
            "hosts": max(hosts_tp, mem_hosts),
            "hosts_throughput": hosts_tp,
            "met_slo": pr.met_slo,
            "p99_ms": pr.summary["p99_ms"],
            "shed_rate": pr.summary["shed_rate"],
            "qps": pr.summary["qps"],
        })
    at_one = next(c for c in curve if c["load_scale"] == 1.0)
    monotone = all(
        curve[i]["replicas"] <= curve[i + 1]["replicas"]
        and curve[i]["hosts"] <= curve[i + 1]["hosts"]
        for i in range(len(curve) - 1))
    out = {
        "construction": label,
        "slo_ms": round(slo_s * 1e3, 3),
        "replicas": at_one["replicas"],
        "hosts": at_one["hosts"],
        "met_slo": at_one["met_slo"],
        "headroom_curve": curve,
        "monotone": monotone,   # True by construction; recorded so the
        #                         gate can assert it from the record
    }
    if memory is not None:
        out["memory"] = memory
    return out
