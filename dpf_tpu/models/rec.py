"""Recommendation (CTR) model with a PIR-maskable embedding table.

TPU-native counterpart of the reference's ``RecModel`` (EmbeddingBag tables
+ 3-layer MLP, ``taobao_rec_dataset_v2.py:30-70``) in flax/optax, plus the
accuracy-vs-PIR-budget evaluation hook (``:199-260``): embeddings of rows a
batch-PIR plan failed to recover are replaced by a sentinel (zero) vector
before inference, and ROC-AUC is reported.
"""

from __future__ import annotations

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .datasets import RecDataset


class RecModel(nn.Module):
    n_items: int
    embed_dim: int = 16
    hidden: int = 64

    @nn.compact
    def __call__(self, hist, hist_len, target):
        emb = nn.Embed(self.n_items, self.embed_dim, name="item_embedding")
        h = emb(hist)                                   # [B, L, D]
        mask = (jnp.arange(h.shape[1])[None, :]
                < hist_len[:, None]).astype(h.dtype)    # [B, L]
        pooled = (h * mask[..., None]).sum(1) / jnp.maximum(
            mask.sum(1, keepdims=True), 1.0)            # mean-pool history
        t = emb(target)                                 # [B, D]
        x = jnp.concatenate([pooled, t, pooled * t], axis=-1)
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(1)(x)[..., 0]                   # logit


def _batches(rng, idx, batch_size):
    idx = rng.permutation(idx)
    for i in range(0, len(idx) - batch_size + 1, batch_size):
        yield idx[i:i + batch_size]


def train_rec_model(ds: RecDataset, epochs=3, batch_size=64, lr=1e-2,
                    embed_dim=16, seed=0):
    """Train; returns (model, params)."""
    model = RecModel(n_items=ds.n_items, embed_dim=embed_dim)
    key = jax.random.PRNGKey(seed)
    params = model.init(key, jnp.zeros((1, ds.max_hist), jnp.int32),
                        jnp.ones((1,), jnp.int32),
                        jnp.zeros((1,), jnp.int32))
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, hist, hist_len, target, label):
        def loss_fn(p):
            logits = model.apply(p, hist, hist_len, target)
            return optax.sigmoid_binary_cross_entropy(logits, label).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    for _ in range(epochs):
        for b in _batches(rng, ds.train_idx, batch_size):
            params, opt_state, _ = step(
                params, opt_state, jnp.asarray(ds.hist[b]),
                jnp.asarray(ds.hist_len[b]), jnp.asarray(ds.target[b]),
                jnp.asarray(ds.label[b]))
    return model, params


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney), no sklearn dependency."""
    order = np.argsort(scores)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # average ranks for ties
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and \
                sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    pos = labels > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def evaluate_with_pir(model, params, ds: RecDataset, pir_optimize=None):
    """Validation ROC-AUC with PIR-unrecovered embeddings masked to zero.

    ``pir_optimize`` is a BatchPIROptimize (or None = no PIR, full access).
    Per validation example, the rows its lookup would touch are fetched with
    the PIR plan; unrecovered ones are served a sentinel embedding
    (reference semantics, ``taobao_rec_dataset_v2.py:199-260``).
    """
    idx = ds.val_idx
    emb_name = "item_embedding"
    # one shared working copy: per example, zero only the touched-but-missing
    # rows and restore them afterwards (O(touched) per example, not O(table))
    table = np.array(params["params"][emb_name]["embedding"])

    @jax.jit
    def apply_fn(tbl, hist, hist_len, target):
        p = {"params": {**params["params"], emb_name: {"embedding": tbl}}}
        return model.apply(p, hist, hist_len, target)

    scores = []
    labels = []
    for i in idx:
        l = int(ds.hist_len[i])
        touched = set(int(x) for x in ds.hist[i, :l]) | {int(ds.target[i])}
        if pir_optimize is None:
            missing = np.empty(0, dtype=np.int64)
        else:
            recovered, _ = pir_optimize.fetch(sorted(touched))
            missing = np.array(sorted(touched - set(recovered)),
                               dtype=np.int64)
        saved = table[missing].copy()
        table[missing] = 0.0
        logit = apply_fn(jnp.asarray(table), jnp.asarray(ds.hist[i:i + 1]),
                         jnp.asarray(ds.hist_len[i:i + 1]),
                         jnp.asarray(ds.target[i:i + 1]))
        table[missing] = saved
        scores.append(float(logit[0]))
        labels.append(float(ds.label[i]))
    return {"roc_auc": roc_auc(np.asarray(labels), np.asarray(scores)),
            "n_eval": len(labels)}
