"""LSTM language model with a PIR-maskable token-embedding table.

TPU-native counterpart of the reference's upstream-style LSTM LM
(``modules/language_model/language_model.py:9-67``) in flax, with the
evaluation hook where token embeddings not recovered by the batch-PIR plan
are dropped (zeroed) during eval (``language_model_dataset.py:148-200``);
reports perplexity instead of AUC.
"""

from __future__ import annotations

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .datasets import LMDataset


class LSTMLanguageModel(nn.Module):
    vocab_size: int
    embed_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, tokens):
        """tokens [B, T] -> logits [B, T, vocab]."""
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       name="token_embedding")
        x = emb(tokens)
        lstm = nn.RNN(nn.LSTMCell(self.hidden), name="lstm")
        h = lstm(x)
        return nn.Dense(self.vocab_size)(h)


def train_lm(ds: LMDataset, epochs=2, batch_size=32, lr=1e-2, seed=0,
             embed_dim=32, hidden=64):
    model = LSTMLanguageModel(vocab_size=ds.vocab_size,
                              embed_dim=embed_dim, hidden=hidden)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, ds.seq_len), jnp.int32))
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]

        def loss_fn(p):
            logits = model.apply(p, inp)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    toks = ds.train_tokens
    for _ in range(epochs):
        for b in range(0, len(toks) - batch_size + 1, batch_size):
            sel = rng.permutation(len(toks))[:batch_size]
            params, opt_state, _ = step(params, opt_state,
                                        jnp.asarray(toks[sel]))
    return model, params


def evaluate_with_pir(model, params, ds: LMDataset, pir_optimize=None):
    """Validation perplexity with unrecovered token embeddings zeroed."""
    emb_name = "token_embedding"
    # shared working copy; zero/restore only the missing rows per example
    table = np.array(params["params"][emb_name]["embedding"])

    @jax.jit
    def loss_fn(tbl, toks):
        p = {"params": {**params["params"], emb_name: {"embedding": tbl}}}
        logits = model.apply(p, toks[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, toks[:, 1:]).mean()

    losses = []
    for row in ds.val_tokens:
        touched = set(int(t) for t in row)
        if pir_optimize is None:
            missing = np.empty(0, dtype=np.int64)
        else:
            recovered, _ = pir_optimize.fetch(sorted(touched))
            missing = np.array(sorted(touched - set(recovered)),
                               dtype=np.int64)
        saved = table[missing].copy()
        table[missing] = 0.0
        loss = loss_fn(jnp.asarray(table), jnp.asarray(row[None, :]))
        table[missing] = saved
        losses.append(float(loss))
    mean_loss = float(np.mean(losses))
    return {"val_loss": mean_loss, "perplexity": float(np.exp(mean_loss)),
            "n_eval": len(losses)}
