"""Synthetic workload datasets + access-pattern extraction.

The reference's batch-PIR experiments run on Taobao CTR, MovieLens, and
WikiText-2 (``paper/experimental/.../modules/*``) — external downloads this
environment cannot fetch (zero egress).  These generators produce statistical
stand-ins with the properties the experiments actually exercise:

* zipf-distributed item popularity (so hot/cold splitting matters),
* user-interest clustering (so co-location finds structure),
* click labels correlated with cluster membership (so a trained model's
  accuracy degrades measurably when PIR fails to recover embeddings),
* a markov token stream for the LM (so context carries information).

Each dataset exposes the same contract the reference modules do
(``taobao_rec_dataset_v2.py:87-197``): train/val *access patterns* — one
set of embedding-table indices per example — plus tensors for model
training and an ``evaluate(pir_optimize)`` hook implemented in rec.py / lm.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class RecDataset:
    """Synthetic CTR dataset: user histories + candidate item + click label."""
    n_items: int
    max_hist: int
    hist: np.ndarray          # [N, max_hist] int32 item ids (0 = pad)
    hist_len: np.ndarray      # [N] int32
    target: np.ndarray        # [N] int32 candidate item
    label: np.ndarray         # [N] float32 click 0/1
    train_idx: np.ndarray
    val_idx: np.ndarray

    def access_patterns(self, split="train"):
        """Embedding rows touched per example (the batch-PIR unit)."""
        idx = self.train_idx if split == "train" else self.val_idx
        out = []
        for i in idx:
            l = int(self.hist_len[i])
            out.append([int(x) for x in self.hist[i, :l]]
                       + [int(self.target[i])])
        return out


def make_rec_dataset(n_items=2000, n_users=400, samples_per_user=6,
                     max_hist=10, n_clusters=20, seed=0,
                     pop_exponent=0.8) -> RecDataset:
    rng = np.random.default_rng(seed)
    # zipf popularity over items, each item assigned an interest cluster
    pop = 1.0 / np.arange(1, n_items + 1) ** pop_exponent
    pop /= pop.sum()
    item_cluster = rng.integers(0, n_clusters, n_items)

    rows = []
    nonempty_clusters = np.unique(item_cluster)
    for _ in range(n_users):
        # pick among clusters that actually own items (small n_items can
        # leave some of the n_clusters empty)
        user_cluster = int(rng.choice(nonempty_clusters))
        cluster_items = np.where(item_cluster == user_cluster)[0]
        for _ in range(samples_per_user):
            l = int(rng.integers(2, max_hist + 1))
            own = rng.choice(cluster_items, size=max(1, l // 2))
            other = rng.choice(n_items, size=l - own.size, p=pop)
            h = np.concatenate([own, other])[:l]
            target = (int(rng.choice(cluster_items)) if rng.random() < 0.5
                      else int(rng.choice(n_items, p=pop)))
            # click iff target matches user's cluster (plus noise)
            label = float(item_cluster[target] == user_cluster)
            if rng.random() < 0.1:
                label = 1.0 - label
            rows.append((h, l, target, label))

    n = len(rows)
    hist = np.zeros((n, max_hist), np.int32)
    hist_len = np.zeros(n, np.int32)
    target = np.zeros(n, np.int32)
    label = np.zeros(n, np.float32)
    for i, (h, l, t, y) in enumerate(rows):
        hist[i, :l] = h
        hist_len[i] = l
        target[i] = t
        label[i] = y
    perm = rng.permutation(n)
    split = int(0.8 * n)
    return RecDataset(n_items=n_items, max_hist=max_hist, hist=hist,
                      hist_len=hist_len, target=target, label=label,
                      train_idx=perm[:split], val_idx=perm[split:])


def make_ratings_dataset(n_items=1500, n_users=300, samples_per_user=8,
                         max_hist=16, n_clusters=12, seed=1) -> RecDataset:
    """MovieLens-style second recommendation workload (reference
    ``modules/movielens_rec/movielens_dataset.py``): same contract as
    ``make_rec_dataset`` but longer histories, flatter popularity, and
    denser per-user activity — a different access-pattern regime for the
    batch-PIR sweeps (bins see more co-access, hot split matters less).
    """
    return make_rec_dataset(n_items=n_items, n_users=n_users,
                            samples_per_user=samples_per_user,
                            max_hist=max_hist, n_clusters=n_clusters,
                            seed=seed, pop_exponent=0.4)


@dataclass
class LMDataset:
    """Synthetic token stream for the LSTM language model."""
    vocab_size: int
    seq_len: int
    train_tokens: np.ndarray  # [n_train, seq_len+1] int32
    val_tokens: np.ndarray    # [n_val, seq_len+1] int32

    def access_patterns(self, split="train"):
        toks = self.train_tokens if split == "train" else self.val_tokens
        return [[int(t) for t in row] for row in toks]


def make_lm_dataset(vocab_size=1000, seq_len=32, n_train=300, n_val=60,
                    seed=0) -> LMDataset:
    rng = np.random.default_rng(seed)
    # first-order markov chain with zipf marginals: contexts are informative
    pop = 1.0 / np.arange(1, vocab_size + 1)
    pop /= pop.sum()
    # each token has a small successor set
    succ = rng.choice(vocab_size, size=(vocab_size, 4), p=pop)

    def sample(n):
        out = np.zeros((n, seq_len + 1), np.int32)
        for i in range(n):
            t = int(rng.choice(vocab_size, p=pop))
            for j in range(seq_len + 1):
                out[i, j] = t
                t = (int(succ[t, rng.integers(0, 4)])
                     if rng.random() < 0.85 else
                     int(rng.choice(vocab_size, p=pop)))
        return out

    return LMDataset(vocab_size=vocab_size, seq_len=seq_len,
                     train_tokens=sample(n_train), val_tokens=sample(n_val))
