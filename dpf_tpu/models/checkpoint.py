"""Model checkpoint/resume via Orbax (SURVEY.md §5: the reference persists
trained workload models to ``model.pt``; this is the TPU-native equivalent).
"""

from __future__ import annotations

import os


def save_params(path: str, params) -> str:
    """Save a flax params pytree; returns the checkpoint path."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    return path


def load_params(path: str, like=None):
    """Load a params pytree saved by save_params.

    `like`: optional abstract/concrete pytree with the target structure
    (restores exact dtypes/shapes); plain restore otherwise.
    """
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        import jax
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          like)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)


def train_or_restore(path: str, init_fn, train_fn):
    """Resume-from-checkpoint pattern.

    ``init_fn() -> (model, params_template)`` must be cheap (model.init on
    dummy inputs); ``train_fn() -> (model, params)`` is the expensive run.
    Restores from `path` when present, otherwise trains and checkpoints.
    """
    if os.path.exists(path):
        model, template = init_fn()
        return model, load_params(path, like=template)
    model, params = train_fn()
    save_params(path, params)
    return model, params
