"""Real-dataset loaders with synthetic fallback.

The reference's workload modules parse the actual Taobao CTR, MovieLens,
and WikiText-2 downloads (``taobao_rec_dataset_v2.py:87-197``,
``movielens_dataset.py:59-113``, ``language_model/data.py`` +
``language_model_dataset.py``).  This environment has zero egress, so the
default experiments run on the statistical stand-ins in ``datasets.py`` —
but the *code path* for real data must exist: these loaders parse the
same file formats into the SAME dataclasses (``RecDataset``/``LMDataset``)
the synthetic generators produce, so every downstream consumer (rec/lm
models, batch-PIR optimizer, sweeps, codesign) works unchanged the moment
the files are dropped in.

File formats (matching the reference's expectations):

* Taobao (``dir/raw_sample.csv`` + ``dir/ad_feature.csv``):
  ``user,time_stamp,adgroup_id,pid,nonclk,clk`` rows; ad ids are
  remapped densely in first-seen order; each interaction's history is
  the user's *clicked* ads before its timestamp.
* MovieLens (``dir/ratings.csv``): ``userId,movieId,rating,timestamp``
  with a header; click := rating >= 4; same history construction.
* WikiText-2 (``dir/train.txt``, ``dir/valid.txt``): whitespace tokens,
  ``<eos>`` appended per line; vocabulary built from the train split
  (optionally capped to the most frequent ``vocab_limit`` words, rest
  mapped to ``<unk>``).

``load_*_or_synthetic`` helpers check the conventional location and fall
back to ``datasets.make_*`` so experiments are runnable either way.
"""

from __future__ import annotations

import bisect
import os
from collections import Counter, defaultdict

import numpy as np

from .datasets import (LMDataset, RecDataset, make_lm_dataset,
                       make_ratings_dataset, make_rec_dataset)


def _interactions_to_rec(rows, n_items, max_hist, split):
    """Shared assembly: (user, item, ts, click) rows -> RecDataset.

    History = the user's clicked items strictly before each row's
    timestamp (most recent ``max_hist``), the reference's
    ``obtain_click_history`` semantics; train/val split by user (first
    ``split`` fraction of users train, rest val), matching the
    reference's user-major split rather than a row shuffle.
    """
    by_user = defaultdict(list)
    for u, i, ts, c in rows:
        by_user[u].append((i, ts, c))

    hist_l, target_l, label_l, user_of = [], [], [], []
    for u, events in by_user.items():
        events.sort(key=lambda e: e[1])
        clicked_items, clicked_ts = [], []    # ts ascending
        for item, ts, click in events:
            # clicked_ts is sorted: the strictly-earlier prefix ends at
            # bisect_left(ts) — O(log E) per event, not a full rescan
            cut = bisect.bisect_left(clicked_ts, ts)
            h = clicked_items[max(0, cut - max_hist):cut]
            hist_l.append(h)
            target_l.append(item)
            label_l.append(float(click))
            user_of.append(u)
            if click:
                clicked_items.append(item)
                clicked_ts.append(ts)

    n = len(hist_l)
    hist = np.zeros((n, max_hist), np.int32)
    hist_len = np.zeros(n, np.int32)
    target = np.array(target_l, np.int32)
    label = np.array(label_l, np.float32)
    for i, h in enumerate(hist_l):
        hist[i, :len(h)] = h
        hist_len[i] = len(h)

    users = list(by_user)
    cut = set(users[:int(split * len(users))])
    tr = np.array([i for i in range(n) if user_of[i] in cut], np.int64)
    va = np.array([i for i in range(n) if user_of[i] not in cut], np.int64)
    return RecDataset(n_items=n_items, max_hist=max_hist, hist=hist,
                      hist_len=hist_len, target=target, label=label,
                      train_idx=tr, val_idx=va)


def load_taobao(data_dir, max_hist=10, split=0.8, limit=None) -> RecDataset:
    """Parse the Taobao ad-click logs (reference
    ``taobao_rec_dataset_v2.py:87-197``).  Requires ``raw_sample.csv``;
    ``ad_feature.csv`` (if present) restricts to ads with features, as
    the reference does when it drops rows without profiles."""
    sample = os.path.join(data_dir, "raw_sample.csv")
    known_ads = None
    feat = os.path.join(data_dir, "ad_feature.csv")
    if os.path.exists(feat):
        with open(feat) as f:
            known_ads = {int(ln.split(",", 2)[0])
                         for ln in f.readlines()[1:] if ln.strip()}
    remap = {}
    rows = []
    with open(sample) as f:
        for i, ln in enumerate(f.readlines()[1:]):
            if limit is not None and i >= limit:
                break
            v = ln.strip().split(",")
            if len(v) < 6:
                continue
            user, ts, ad, clk = int(v[0]), int(v[1]), int(v[2]), int(v[5])
            if known_ads is not None and ad not in known_ads:
                continue        # no ad profile (reference skips these)
            if ad not in remap:
                remap[ad] = len(remap)
            rows.append((user, remap[ad], ts, clk))
    if not rows:
        raise ValueError("no usable rows in %s" % sample)
    return _interactions_to_rec(rows, len(remap), max_hist, split)


def load_movielens(data_dir, max_hist=16, split=0.8,
                   limit=None) -> RecDataset:
    """Parse MovieLens ``ratings.csv`` (reference
    ``movielens_dataset.py:59-113``): click := rating >= 4; movie ids
    remapped densely in first-seen order."""
    path = os.path.join(data_dir, "ratings.csv")
    remap = {}
    rows = []
    with open(path) as f:
        for i, ln in enumerate(f.readlines()[1:]):
            if limit is not None and i >= limit:
                break
            v = ln.strip().split(",")
            if len(v) < 4:
                continue
            user, movie = int(v[0]), int(v[1])
            click = float(v[2]) >= 4.0
            ts = int(v[3])
            if movie not in remap:
                remap[movie] = len(remap)
            rows.append((user, remap[movie], ts, int(click)))
    if not rows:
        raise ValueError("no usable rows in %s" % path)
    return _interactions_to_rec(rows, len(remap), max_hist, split)


def load_wikitext(data_dir, seq_len=32, vocab_limit=None) -> LMDataset:
    """Parse WikiText-style token files (reference
    ``language_model/data.py``): whitespace split, ``<eos>`` per line;
    vocab from the train split, optional most-frequent cap with
    ``<unk>`` = 0."""
    def read_tokens(name):
        toks = []
        with open(os.path.join(data_dir, name), encoding="utf8") as f:
            for ln in f:
                toks.extend(ln.split() + ["<eos>"])
        return toks

    train_toks = read_tokens("train.txt")
    val_toks = read_tokens("valid.txt")

    if vocab_limit:
        common = [w for w, _ in Counter(train_toks).most_common(
            vocab_limit - 1)]
        word2idx = {"<unk>": 0}
        for w in common:
            word2idx[w] = len(word2idx)
    else:
        word2idx = {}
        for w in train_toks:
            if w not in word2idx:
                word2idx[w] = len(word2idx)

    def encode(toks):
        unk = word2idx.get("<unk>", 0)
        ids = np.array([word2idx.get(w, unk) for w in toks], np.int32)
        n_seq = ids.size // (seq_len + 1)
        if n_seq == 0:
            raise ValueError("split too small for seq_len=%d" % seq_len)
        return ids[:n_seq * (seq_len + 1)].reshape(n_seq, seq_len + 1)

    return LMDataset(vocab_size=len(word2idx), seq_len=seq_len,
                     train_tokens=encode(train_toks),
                     val_tokens=encode(val_toks))


# Conventional data locations (the reference hardcodes ./data/<name>/)
DATA_ROOT = os.environ.get("DPF_DATA_ROOT", "data")


def _dir(name):
    return os.path.join(DATA_ROOT, name)


def load_taobao_or_synthetic(**kw):
    d = _dir("taobao")
    if os.path.exists(os.path.join(d, "raw_sample.csv")):
        return load_taobao(d, **kw)
    return make_rec_dataset()


def load_movielens_or_synthetic(**kw):
    d = _dir("ml-20m")
    if os.path.exists(os.path.join(d, "ratings.csv")):
        return load_movielens(d, **kw)
    return make_ratings_dataset()


def load_wikitext_or_synthetic(**kw):
    d = _dir("wikitext-2")
    if os.path.exists(os.path.join(d, "train.txt")):
        return load_wikitext(d, **kw)
    return make_lm_dataset()
