"""End-to-end serving observability (docs/OBSERVABILITY.md).

Three coupled pieces, one per module:

* ``tracer``  — per-batch span tracing (``submit`` > ``admit`` /
  ``pack`` / ``dispatch``, ``wait``, ``decode``, ``route``, ``retry``,
  ``failover``, ``rebuild``), bounded ring, JSONL + Chrome-trace
  export for Perfetto, joint host+device digest via
  ``utils.profiling.summarize_trace``.  Off by default — the serving
  hot path pays one global read (``span()`` returns the shared no-op).
* ``metrics`` — typed Counter/Gauge/Histogram registry with an
  OpenMetrics text exporter and JSON snapshot; ``EngineCounters``,
  ``CacheCounters``, ``SWALLOWED_ERRORS``, breaker states and the
  router's EWMA cost table self-register as first-class series.
* ``flight``  — a bounded ring of recent structured DECISIONS (route,
  shed, breaker transition, retry, failover, injected fault, rebuild),
  dumpable on demand and embedded in benchmark records.

``benchmark.py --trace`` (``obs/bench_trace.py``) captures a joint
host+device profile for one tuned shape and measures the whole stack's
overhead (committed record: BENCH_TRACE_r12.json).
"""

from .flight import FLIGHT, FlightRecorder, flight_dump  # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, default_registry,
                      register_cluster, register_engine, register_router)
from .tracer import (NULL_SPAN, Span, Tracer, disable,  # noqa: F401
                     enable, get_tracer, joint_digest, span, tracing)


def set_process_index(index: int | None) -> None:
    """Label THIS process's observability output with its jax
    ``process_index`` (multi-host serving): flight-recorder events gain
    a ``process`` attribute and engine/router/cluster metric series a
    ``process`` label, so merged cross-host dumps stay attributable.
    ``multihost.initialize`` calls this on success; cluster workers set
    their rank explicitly."""
    from .flight import set_process_index as _flight
    from .metrics import set_process_index as _metrics
    _flight(index)
    _metrics(index)


def record_sections(flight_last: int = 64) -> dict:
    """The observability sections every benchmark record embeds:
    ``metrics`` (the registry JSON snapshot), ``flight`` (the tail of
    the decision ring), and — when a tracer is installed —
    ``trace_digest`` (host span self-times).  Small and JSON-ready."""
    out = {"metrics": REGISTRY.snapshot(),
           "flight": flight_dump(last=flight_last)}
    t = get_tracer()
    if t is not None:
        out["trace_digest"] = t.digest()
    return out
