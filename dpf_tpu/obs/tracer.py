"""Per-batch span tracing for the serving stack.

``jax.profiler`` answers "where did DEVICE time go" (op-level tracks,
``utils/profiling.summarize_trace``); nothing answered the same
question for the HOST half of a served batch — the decode, the bucket
pad, the admission wait, the blocking ``np.asarray`` — even though the
engine's aggregate counters prove the host side dominates on the
synchronous CPU backend.  This module is the host-side mirror: a
lightweight ``Tracer`` producing NESTED spans (``submit`` > ``admit`` /
``pack`` / ``dispatch``, ``wait``, ``decode``, plus the router's
``route`` / ``retry`` / ``failover`` and the supervisor's ``rebuild``),
carried through ``ServingEngine.submit``/``_resolve_one``,
``SchemeRouter``, ``EngineSupervisor`` and ``LookupStream`` via the
module-level ``span()`` helper.

Design constraints (docs/OBSERVABILITY.md):

* **Tracing-off fast path** — ``span()`` with no tracer installed
  returns one shared no-op context manager: a single global read on the
  serving hot path, no allocation.  The load harness's overhead leg
  (``benchmark.py --trace``) measures the on/off qps delta and the
  committed record keeps it under 2%.
* **Bounded memory** — finished spans land in a ring
  (``deque(maxlen=capacity)``); a long-lived serving process keeps the
  most recent window, like the latency ring.
* **Perfetto-ready export** — ``export_chrome()`` writes the Chrome
  trace-event JSON Perfetto opens directly, so host spans sit alongside
  a ``jax.profiler`` device trace of the same run; ``joint_digest``
  merges the two into the one small digest benchmark records embed
  (extending ``summarize_trace``'s ncu-report role to the host).

Spans are thread-aware (one nesting stack per thread, thread id on
every span), so supervisor rebuilds and background resolution show up
on their own tracks.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque

from .flight import _env_capacity

#: default bounded span-ring capacity per tracer
SPAN_RING = 8192


class NullSpan:
    """The shared no-op span: ``span()``'s answer when tracing is off.

    Stateless and reentrant — one instance serves every call site
    concurrently, so the off path costs a global read and nothing else.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = NullSpan()


class Span:
    """One live span; use as a context manager (``Tracer.span``).

    ``set(**attrs)`` attaches attributes any time before exit (e.g. the
    routed construction, the bucket size).  On exit the span computes
    its SELF time (duration minus direct children — the same
    double-count subtraction ``summarize_trace`` applies to profiler
    tracks) and lands in the tracer's ring.
    """

    __slots__ = ("name", "attrs", "span_id", "parent_id", "tid",
                 "t0", "dur_s", "_children_s", "_tracer")

    def __init__(self, tracer, name, span_id, parent_id, tid, attrs):
        self._tracer = tracer
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.tid = tid
        self.attrs = attrs
        self.t0 = None
        self.dur_s = 0.0
        self._children_s = 0.0

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._tracer._push(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_s = time.perf_counter() - self.t0
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer._pop(self)
        return False


class Tracer:
    """Bounded-ring span recorder; install process-wide via ``enable()``.

    All methods are thread-safe; each thread keeps its own nesting
    stack so concurrent submits/rebuilds produce correctly-parented
    spans on separate tracks.
    """

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _env_capacity("DPF_SPAN_RING", SPAN_RING)
        self._ring = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._epoch = time.perf_counter()
        self.dropped = 0          # spans evicted from the full ring
        self.recorded = 0

    # ------------------------------------------------------- recording

    def span(self, name: str, **attrs) -> Span:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1].span_id if stack else None
        return Span(self, name, next(self._ids), parent,
                    threading.get_ident(), attrs)

    def _push(self, sp: Span):
        self._local.stack.append(sp)

    def _pop(self, sp: Span):
        stack = self._local.stack
        # tolerate exotic unwinds: pop through to this span
        while stack and stack[-1] is not sp:
            stack.pop()
        if stack:
            stack.pop()
        if stack:
            stack[-1]._children_s += sp.dur_s
        row = {"name": sp.name, "span_id": sp.span_id,
               "parent_id": sp.parent_id, "tid": sp.tid,
               "ts_us": round((sp.t0 - self._epoch) * 1e6, 1),
               "dur_us": round(sp.dur_s * 1e6, 1),
               "self_us": round(max(0.0, sp.dur_s - sp._children_s)
                                * 1e6, 1)}
        if sp.attrs:
            row["attrs"] = sp.attrs
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(row)
            self.recorded += 1

    # --------------------------------------------------------- reading

    def events(self) -> list:
        """Finished spans, oldest first (each a JSON-ready dict)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self.recorded = 0

    def digest(self, top: int = 12) -> dict | None:
        """Aggregate SELF time per span name — the host-side half of
        the joint digest (mirrors ``summarize_trace``'s shape: small
        enough to embed in a benchmark record)."""
        events = self.events()
        if not events:
            return None
        by_name = {}
        total_us = 0.0
        for e in events:
            s = e["self_us"]
            total_us += s
            cnt, us = by_name.get(e["name"], (0, 0.0))
            by_name[e["name"]] = (cnt + 1, us + s)
        spans = sorted(by_name.items(), key=lambda kv: -kv[1][1])[:top]
        return {"spans_recorded": self.recorded,
                "spans_dropped": self.dropped,
                "host_ms": round(total_us / 1e3, 3),
                "top_spans": [{"span": k, "count": c,
                               "ms": round(us / 1e3, 3)}
                              for k, (c, us) in spans]}

    # --------------------------------------------------------- exports

    def export_jsonl(self, path: str) -> int:
        """One span per line; returns the span count."""
        events = self.events()
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (``ph="X"`` complete events, µs
        timestamps) — open in Perfetto (ui.perfetto.dev) next to the
        ``jax.profiler`` device trace of the same run."""
        events = [{"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
                   "args": {"name": "dpf_tpu host spans"}}]
        tids = {}
        for e in self.events():
            tid = tids.setdefault(e["tid"], len(tids))
            ev = {"ph": "X", "pid": 1, "tid": tid, "name": e["name"],
                  "ts": e["ts_us"], "dur": e["dur_us"]}
            if "attrs" in e:
                ev["args"] = {k: str(v) for k, v in e["attrs"].items()}
            events.append(ev)
        for raw, tid in tids.items():
            events.append({"ph": "M", "pid": 1, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": "host thread %d" % raw}})
        return {"traceEvents": events,
                "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# ------------------------------------------------- process-wide tracer

_TRACER: Tracer | None = None


def enable(capacity: int | None = None) -> Tracer:
    """Install (and return) the process tracer; idempotent unless a
    different capacity is requested.  ``capacity=None`` resolves the
    ``DPF_SPAN_RING`` environment knob (else ``SPAN_RING``)."""
    global _TRACER
    if capacity is None:
        capacity = _env_capacity("DPF_SPAN_RING", SPAN_RING)
    if _TRACER is None or _TRACER._ring.maxlen != int(capacity):
        _TRACER = Tracer(capacity)
    return _TRACER


def disable() -> None:
    """Remove the process tracer: ``span()`` reverts to the no-op fast
    path (already-captured spans are dropped with the tracer)."""
    global _TRACER
    _TRACER = None


def get_tracer() -> Tracer | None:
    return _TRACER


def tracing() -> bool:
    return _TRACER is not None


def span(name: str, **attrs):
    """THE hot-path entry point: a real span when tracing is enabled,
    the shared ``NULL_SPAN`` otherwise (one global read, no alloc)."""
    t = _TRACER
    if t is None:
        return NULL_SPAN
    return t.span(name, **attrs)


# ---------------------------------------------------------- digesting

def joint_digest(tracer: Tracer | None = None,
                 trace_dir: str | None = None, top: int = 12) -> dict:
    """The one digest benchmark records embed: host span self-times
    (this module) merged with the device op self-times
    (``utils.profiling.summarize_trace`` over a ``jax.profiler``
    capture of the same run).  Either half may be absent (no tracer /
    no profiler capture); ``total_ms`` sums whatever is present."""
    host = None
    t = tracer if tracer is not None else _TRACER
    if t is not None:
        host = t.digest(top=top)
    device = None
    if trace_dir:
        from ..utils.profiling import summarize_trace
        device = summarize_trace(trace_dir, top=top)
    total = sum(d[k] for d, k in ((host, "host_ms"),
                                  (device, "device_ms")) if d)
    return {"host": host, "device": device,
            "total_ms": round(total, 3)}
