"""Observability benchmark: joint trace digest, OpenMetrics export,
flight-recorder fault attribution, and the tracing overhead bound.

``benchmark.py --trace``.  Four legs over one tuned serving shape
(entries=4096, entry_size=16, cap=128 — the PR-6 load-bench point),
committed as ``BENCH_TRACE_r12.json``:

* **profile** — a short closed-loop burst through the cost-model
  router with BOTH capture layers on: the host span tracer
  (``obs.tracer``) and a ``jax.profiler`` device trace of the same
  run.  The record embeds ``joint_digest`` — host span self-times
  merged with device op self-times — the one digest that says where a
  served batch's time went on each side of the dispatch boundary.
* **openmetrics** — the full OpenMetrics text exposition after that
  traffic: per-engine counters + latency histogram, per-construction
  breaker state, the router's EWMA cost table, routing provenance.
  The gate asserts the engine/router/breaker families are present.
* **chaos flight** — a replay slice under a seeded fault plan
  (``serve.faults``) through ``submit_resilient``; the flight
  recorder's ring is then JOINED on the arrival index: every injected
  fault event must attribute back to the route decision that placed
  its batch (construction + arrival match).  The gate asserts ≥ 1
  attributed fault — the attribution story, demonstrated end to end.
* **overhead** — the whole observability stack's cost: the identical
  closed-loop replay of the PR-6 bursty trace (seed 11), tracing OFF
  vs ON (spans recording into the ring), measured as adjacent paired
  segment replays and scored by the median paired delta (ambient host
  load swings far more than the effect under test).  The gate bounds
  the delta at 2% — observability cheap enough to leave on in
  production.

The replay here is CLOSED-loop (back-to-back, in arrival order) where
the load bench is open-loop: an open-loop replay's qps is bound by the
arrival schedule, which would hide any tracing overhead entirely —
back-to-back submission is the honest denominator.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --trace [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json
import time
from collections import deque

import numpy as np

from ..serve import loadgen
from ..serve.bench_load import _batch_for, _key_pool
from ..utils.profiling import trace as profiler_trace
from . import tracer as obs_tracer
from .flight import FLIGHT, flight_dump
from .metrics import REGISTRY
from .tracer import joint_digest

#: OpenMetrics families the gate requires (engine / router / breaker /
#: flight coverage — the first-class series the ISSUE names)
REQUIRED_FAMILIES = (
    "dpf_engine_batches_submitted_total",
    "dpf_engine_latency_seconds_bucket",
    "dpf_router_cost_seconds",
    "dpf_router_routed_from_total",
    "dpf_breaker_state",
    "dpf_flight_events_total",
)


def _closed_loop(submit, sizes, *, window: int = 8) -> float:
    """Back-to-back replay of ``sizes`` through ``submit(j, b)``
    (returns a future); returns the makespan in seconds."""
    t0 = time.perf_counter()
    outstanding = deque()
    for j, b in enumerate(sizes):
        while len(outstanding) >= window:
            outstanding.popleft().result()
        outstanding.append(submit(j, b))
    while outstanding:
        outstanding.popleft().result()
    return time.perf_counter() - t0


def _router_submit(router, pools):
    def submit(j, b):
        dec = router.route(b)
        keys, _ = _batch_for(pools[dec.construction], j, b)
        return router.submit(dec, keys)
    return submit


def _attribute_faults(events) -> list:
    """Join fault events to the route decision that placed their batch:
    same arrival index AND same construction.  Returns
    ``[{fault, route}]`` pairs — the attribution the flight recorder
    exists to answer."""
    routes = {}
    for e in events:
        if e["kind"] == "route" and "arrival" in e:
            routes[(e["arrival"], e["construction"])] = e
    out = []
    for e in events:
        if e["kind"] != "fault":
            continue
        rt = routes.get((e["arrival"], e["construction"]))
        if rt is not None:
            out.append({"fault": e, "route": rt})
    return out


def trace_bench(n=4096, entry_size=16, cap=128, prf=0, *, seed=11,
                duration_s=7.0, on_rate=320.0, distinct=16, reps=3,
                window=8, profile_arrivals=48, constructions=None,
                trace_dir="/tmp/dpf_tpu_traces", overhead_gate=True,
                quiet=False) -> dict:
    """Run all four observability legs; returns the ``--trace`` record."""
    from ..serve.faults import FaultPlan, FaultSpec, RetryPolicy
    from ..serve.router import LABELS, SchemeRouter

    labels = tuple(constructions or LABELS)
    FLIGHT.clear()          # scope the ring to this bench
    table = np.random.default_rng(seed ^ 0x0b5).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    # the PR-6 load-bench arrival process, replayed closed-loop
    arrivals = loadgen.bursty_trace(
        on_rate=on_rate, off_rate=2.0, on_s=1.0, off_s=2.0,
        duration_s=duration_s, cap=cap, seed=seed, n=n)
    sizes = loadgen.batch_sizes(arrivals)
    total_q = sum(sizes)

    router = SchemeRouter(table, prf=prf, cap=cap, probe=True,
                          constructions=labels)
    pools = {lb: _key_pool(router.server(lb), n, distinct,
                           b"trace-%s" % lb.encode()) for lb in labels}
    submit = _router_submit(router, pools)

    # ---- leg 1: joint host+device profile over a short burst ---------
    t = obs_tracer.enable()
    t.clear()
    cfg = "obs_trace_n%d_e%d_cap%d" % (n, entry_size, cap)
    with profiler_trace(cfg, base_dir=trace_dir) as tdir:
        _closed_loop(submit, sizes[:profile_arrivals], window=window)
    joint = joint_digest(tracer=t, trace_dir=tdir)
    host_spans = {s["span"] for s in
                  (joint["host"] or {}).get("top_spans", ())}
    spans_jsonl = "%s/host_spans.jsonl" % tdir
    chrome_json = "%s/host_spans.chrome.json" % tdir
    t.export_jsonl(spans_jsonl)
    t.export_chrome(chrome_json)     # open next to the device trace in
    #                                  Perfetto (docs/OBSERVABILITY.md)
    obs_tracer.disable()

    # ---- leg 2: the OpenMetrics exposition after that traffic --------
    text = REGISTRY.openmetrics()
    families_present = {f: (("\n%s" % f) in ("\n" + text))
                        for f in REQUIRED_FAMILIES}

    # ---- leg 3: chaos slice -> flight-recorder fault attribution -----
    plan = FaultPlan([
        # max_fires < the retry policy's max_attempts: one arrival can
        # absorb every remaining fire and still succeed on its last
        # attempt, so the chaos slice never fails a batch outright
        FaultSpec(kind="dispatch_error", start=2, stop=24, p=0.5,
                  max_fires=3),
        FaultSpec(kind="latency", start=4, stop=24, p=0.25,
                  latency_s=0.005, max_fires=4),
    ], seed=seed)
    inj = plan.injector()
    chaos_router = SchemeRouter(
        None, servers={lb: router.server(lb) for lb in labels},
        cap=cap, probe=True, injector=inj,
        retry=RetryPolicy(max_attempts=4, backoff_s=0.001, seed=seed))
    flight_mark = FLIGHT.recorded

    def chaos_submit(j, b):
        inj.begin_arrival(j)
        return chaos_router.submit_resilient(
            b, lambda lb: _batch_for(pools[lb], j, b)[0])
    chaos_sizes = sizes[:max(24, profile_arrivals)]
    _closed_loop(chaos_submit, chaos_sizes, window=window)
    chaos_events = [e for e in flight_dump()
                    if e["seq"] > flight_mark]
    attributed = _attribute_faults(chaos_events)

    # ---- leg 4: tracing-on vs tracing-off qps (closed loop) ----------
    # one untimed full pass first (the earlier legs only touched a
    # prefix of the trace, so the first timed measurement would
    # otherwise eat the remaining bucket warmup).  Ambient load on a
    # shared host swings whole seconds between passes — far more than
    # the sub-percent effect under test — so only measurements taken
    # BACK-TO-BACK are comparable: the replay is split into contiguous
    # segments, each segment timed as an adjacent (off, on) pair with
    # the leg order alternating, and the score is the MEDIAN of the
    # paired relative deltas (drops the pairs a load spike still split).
    _closed_loop(submit, sizes, window=window)

    def timed(tracing_on: bool, seg) -> float:
        if tracing_on:
            obs_tracer.enable()
        else:
            obs_tracer.disable()
        try:
            return _closed_loop(submit, seg, window=window)
        finally:
            obs_tracer.disable()
    nseg = min(12, max(1, len(sizes) // 8))
    bounds = [i * len(sizes) // nseg for i in range(nseg + 1)]
    segments = [sizes[a:b] for a, b in zip(bounds, bounds[1:]) if b > a]
    deltas, mk_off, mk_on = [], 0.0, 0.0
    pair = 0
    for _ in range(max(1, reps)):
        for seg in segments:
            t = {}
            for on in ((False, True) if pair % 2 == 0
                       else (True, False)):
                t[on] = timed(on, seg)
            pair += 1
            mk_off += t[False]
            mk_on += t[True]
            deltas.append((t[True] - t[False]) / t[False] * 100.0)
    deltas.sort()
    mid = len(deltas) // 2
    median_pct = (deltas[mid] if len(deltas) % 2
                  else (deltas[mid - 1] + deltas[mid]) / 2.0)
    # makespans are per-leg SUMS over every pair (reps full replays)
    mk_off /= max(1, reps)
    mk_on /= max(1, reps)
    qps_off = int(total_q / mk_off)
    qps_on = int(total_q / mk_on)
    overhead_pct = round(median_pct, 3)

    record = {
        "metric": "end-to-end serving observability: per-batch span "
                  "tracing + jax.profiler joint digest, OpenMetrics "
                  "export, flight-recorder fault attribution, and the "
                  "full-stack tracing overhead (entries=%d, "
                  "entry_size=%d, prf=%d, cap=%d, closed-loop replay "
                  "of the seeded bursty trace: %d arrivals / %d "
                  "queries, 1 device)"
                  % (n, entry_size, prf, cap, len(sizes), total_q),
        "value": overhead_pct,
        "unit": "percent makespan overhead, tracing on vs off (median "
                "of paired adjacent segment replays)",
        "vs_baseline": round(qps_on / qps_off, 4) if qps_off else None,
        "baseline": "the identical closed-loop replay with the span "
                    "tracer disabled (flight recorder + counters stay "
                    "on in both legs — they are always-on)",
        "trace": {"kind": "bursty", "seed": seed,
                  "duration_s": duration_s, "on_rate": on_rate,
                  "arrivals": len(sizes), "queries": total_q,
                  "cap": cap, "reps": reps, "window": window},
        "constructions": list(labels),
        "profile": {
            "config": cfg,
            "arrivals": profile_arrivals,
            "joint_digest": joint,
            "host_spans_jsonl": spans_jsonl,
            "host_spans_chrome": chrome_json,
        },
        "openmetrics": {
            "families_required": dict(families_present),
            "lines": len(text.splitlines()),
            "text": text,
        },
        "chaos_flight": {
            "plan": plan.as_dict(),
            "injected": dict(inj.injected),
            "events": len(chaos_events),
            "attributed_faults": len(attributed),
            "attribution_sample": attributed[:4],
            "flight_tail": chaos_events[-48:],
        },
        "overhead": {
            "qps_tracing_off": qps_off,
            "qps_tracing_on": qps_on,
            "makespan_off_s": round(mk_off, 4),
            "makespan_on_s": round(mk_on, 4),
            "segments": len(segments),
            "pairs": pair,
            "paired_deltas_pct": [round(d, 3) for d in deltas],
            "overhead_pct": overhead_pct,
            "bound_pct": 2.0,
            # the dryrun's segments are tens of ms — far below what the
            # paired estimator can resolve — so it measures but does
            # not gate ("no perf claims")
            "gated": bool(overhead_gate),
        },
        "checked": bool(
            joint["host"] is not None
            and {"submit", "dispatch"} <= host_spans
            and joint["device"] is not None
            and joint["device"]["device_ms"] > 0
            and all(families_present.values())
            and len(attributed) >= 1
            and (not overhead_gate or overhead_pct <= 2.0)),
    }
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--duration", type=float, default=7.0,
                    help="trace duration in seconds")
    ap.add_argument("--on-rate", type=float, default=320.0,
                    help="burst arrival rate (arrivals/sec in ON "
                         "windows)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--trace-dir", default="/tmp/dpf_tpu_traces")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): exercises every "
                         "leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.dryrun:
        record = trace_bench(n=512, entry_size=8, cap=16, prf=args.prf,
                             seed=args.seed, duration_s=1.5,
                             on_rate=30.0, distinct=8, reps=1,
                             profile_arrivals=12,
                             constructions=("logn", "radix4"),
                             trace_dir=args.trace_dir,
                             overhead_gate=False)
    else:
        record = trace_bench(n=args.n, entry_size=args.entry_size,
                             cap=args.cap, prf=args.prf, seed=args.seed,
                             duration_s=args.duration,
                             on_rate=args.on_rate, reps=args.reps,
                             trace_dir=args.trace_dir)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
