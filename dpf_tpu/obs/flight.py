"""Flight recorder: a bounded ring of recent routing/fault decisions.

The serving stack makes runtime decisions on every batch — cost-model
routing, admission control, retries, breaker trips, supervisor
rebuilds — and until now the only evidence was aggregate counter sums:
a p99 regression or a mis-routed burst could not be attributed to a
DECISION after the fact.  The flight recorder is the attribution
substrate: every decision point appends one small structured event to
a process-wide bounded ring, dumpable on demand (``flight_dump()``),
embedded in benchmark records, and dumped automatically when the chaos
bench's equality gate fails so an escape is diagnosable.

Event kinds (full schema in docs/OBSERVABILITY.md):

* ``route``    — construction, routed_from, bucket, batch, the cost
  estimates the argmin saw, and (under fault injection) the arrival
  index — the join key that attributes a later fault to the decision
  that placed the batch.
* ``shed`` / ``deadline`` — admission control rejections and
  cooperative-deadline trips, with the queue state that triggered them.
* ``breaker``  — every breaker state transition.
* ``retry`` / ``failover`` — resilient-submit recovery steps.
* ``fault``    — every injected-fault fire (kind, construction,
  bucket, arrival), written by ``FaultInjector``.
* ``rebuild``  — supervisor engine rebuilds (ok/failed).
* ``scatter`` / ``host_drop`` / ``cluster_recovery`` — the multi-host
  tier (``parallel/cluster.py``): per-arrival scatter plans, detected
  host losses, and the re-shard-or-degrade decision that answered each
  loss (``decision`` ∈ {"reshard", "degrade"}).

Events carry a monotonic timestamp relative to recorder start and a
global sequence number, so interleavings across threads stay ordered.
Multi-host runs stamp each event with the recording process's
``process`` index (``set_process_index``), so merged rings stay
attributable per host.
Recording is always on: one dict + deque append per DECISION (not per
query), bounded memory, no I/O — the ``--trace`` bench's overhead leg
measures the full observability stack under 2% of qps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

#: default bounded flight-ring capacity (events, not queries)
FLIGHT_RING = 2048


def _env_capacity(name: str, default: int) -> int:
    """Positive-int ring capacity from the environment, else the
    default (a malformed value must never break recorder import)."""
    try:
        v = int(os.environ.get(name, ""))
        return v if v > 0 else default
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    """Thread-safe bounded event ring; one process-wide instance
    (``FLIGHT``) is the default everywhere.

    ``capacity`` defaults to ``DPF_FLIGHT_RING`` from the environment
    (else ``FLIGHT_RING``) — a busy multi-tenant process can widen the
    ring without code changes.  ``dropped`` counts events evicted from
    a full ring (exported as ``dpf_flight_events_dropped_total``), so
    ring overrun is visible instead of silently losing
    fault-attribution evidence."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = _env_capacity("DPF_FLIGHT_RING", FLIGHT_RING)
        self._ring = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.recorded = 0           # total ever recorded (ring evicts)
        self.dropped = 0            # events evicted from the full ring
        self._process = None        # jax process_index label (multi-host)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def set_process(self, index: int | None) -> None:
        """Stamp every subsequent event with a ``process`` label — the
        ``jax.process_index()`` of this process (``multihost.initialize``
        calls this on success; cluster workers set their rank), so a
        multi-host flight merge stays attributable per host."""
        self._process = None if index is None else int(index)

    def record(self, kind: str, **attrs) -> None:
        """Append one event; never raises (decision paths call this)."""
        try:
            ev = {"seq": 0, "t": round(time.monotonic() - self._t0, 6),
                  "kind": kind}
            if self._process is not None and "process" not in attrs:
                ev["process"] = self._process
            ev.update(attrs)
            with self._lock:
                self.recorded += 1
                ev["seq"] = self.recorded
                if len(self._ring) == self._ring.maxlen:
                    self.dropped += 1
                self._ring.append(ev)
        except Exception:
            pass

    def dump(self, last: int | None = None) -> list:
        """JSON-ready copy of the ring, oldest first (``last`` bounds
        the tail for embedding in records)."""
        with self._lock:
            out = list(self._ring)
        if last is not None:
            out = out[-int(last):]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
        # `recorded` keeps counting: it is a monotonic metric

    def export_jsonl(self, path: str) -> int:
        events = self.dump()
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)


#: the process flight recorder (serving code records into this)
FLIGHT = FlightRecorder()


def flight_dump(last: int | None = None) -> list:
    """Dump the process flight ring (the on-demand diagnosis entry
    point named by docs/OBSERVABILITY.md)."""
    return FLIGHT.dump(last=last)


def set_process_index(index: int | None) -> None:
    """Label the process ring's events with a process index
    (multi-host serving: one flight ring per process, merged by rank)."""
    FLIGHT.set_process(index)
