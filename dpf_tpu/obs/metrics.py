"""Typed metrics registry with OpenMetrics/Prometheus text export.

The serving stack already keeps rich counters — ``EngineCounters``,
``CacheCounters``, ``SWALLOWED_ERRORS``, breaker states, the router's
EWMA cost table — but each lives behind its own ad-hoc ``as_dict`` and
none is scrapeable as a time series.  This module gives them one
registry:

* **Primitives** — ``Counter`` (monotonic), ``Gauge`` (set/observe),
  ``Histogram`` (fixed buckets, complementing the latency ring's exact
  quantiles with mergeable cumulative counts).  All label-aware
  (``.labels(construction="logn").inc()``) and thread-safe — the
  supervisor's rebuild threads and ``RoutedFuture.result()`` callers
  mutate concurrently.
* **Collectors** — live objects export through *collector callbacks*
  run at scrape time, held by WEAK reference: a GC'd engine's series
  vanish from the next scrape instead of leaking forever (tests and
  benches build hundreds of short-lived engines per process).
  ``ServingEngine`` and ``SchemeRouter`` self-register on
  construction; ``CacheCounters``/``SWALLOWED_ERRORS`` are registered
  once at import.
* **Exports** — ``openmetrics()`` renders the Prometheus/OpenMetrics
  text exposition (``# TYPE``/``# HELP`` headers, ``_total`` counter
  samples, ``le``-bucketed histograms, terminated by ``# EOF``) and
  ``snapshot()`` the JSON equivalent benchmark records embed.

Metric names and the full series table are documented in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import threading
import weakref

#: default histogram bucket upper bounds (seconds) for serving
#: latencies — the SAME ladder ``EngineCounters`` accumulates into, so
#: ``observe_counts`` folds engine histograms in without resampling
from ..utils.profiling import LATENCY_HIST_BUCKETS_S as LATENCY_BUCKETS_S


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: tuple, extra: tuple = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, str(v).replace('"', r'\"'))
                             for k, v in items)


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared label/value plumbing; subclasses define ``kind``."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values = {}            # label key tuple -> state

    def labels(self, **labels) -> "_Child":
        return _Child(self, _label_key(labels))

    # state management ------------------------------------------------
    def _zero(self):
        return 0.0

    def _get(self, key: tuple):
        with self._lock:
            if key not in self._values:
                self._values[key] = self._zero()
            return self._values[key]

    def samples(self) -> list:
        """[(suffix, label_key, extra_labels, value)] for rendering."""
        with self._lock:
            return [("", k, (), v) for k, v in sorted(self._values.items())]

    def snapshot_value(self, state):
        return state


class _Child:
    __slots__ = ("_m", "_key")

    def __init__(self, metric, key):
        self._m = metric
        self._key = key

    def inc(self, amount=1):
        return self._m.inc(amount, _key=self._key)

    def set(self, value):
        return self._m.set(value, _key=self._key)

    def observe(self, value):
        return self._m.observe(value, _key=self._key)

    @property
    def value(self):
        return self._m._get(self._key)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount=1, *, _key=()):
        if amount < 0:
            raise ValueError("counters only go up (got %r)" % (amount,))
        with self._lock:
            self._values[_key] = self._values.get(_key, 0.0) + amount

    @property
    def value(self):
        return self._get(())

    def samples(self) -> list:
        with self._lock:
            return [("_total", k, (), v)
                    for k, v in sorted(self._values.items())]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, *, _key=()):
        with self._lock:
            self._values[_key] = float(value)

    def inc(self, amount=1, *, _key=()):
        with self._lock:
            self._values[_key] = self._values.get(_key, 0.0) + amount

    @property
    def value(self):
        return self._get(())


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (+Inf implicit)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=LATENCY_BUCKETS_S):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("need at least one bucket bound")

    def _zero(self):
        return {"counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "count": 0}

    def observe(self, value, *, _key=()):
        v = float(value)
        with self._lock:
            st = self._values.setdefault(_key, self._zero())
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1

    def observe_counts(self, counts, sum_, count, *, _key=()):
        """Fold pre-aggregated per-bucket counts in (the
        ``EngineCounters`` latency histogram path: observations happen
        in the engine, the registry only renders)."""
        with self._lock:
            st = self._values.setdefault(_key, self._zero())
            for i, c in enumerate(counts):
                st["counts"][i] += int(c)
            st["sum"] += float(sum_)
            st["count"] += int(count)

    def samples(self) -> list:
        out = []
        with self._lock:
            for k, st in sorted(self._values.items()):
                acc = 0
                for b, c in zip(self.buckets, st["counts"]):
                    acc += c
                    out.append(("_bucket", k, (("le", _fmt(b)),), acc))
                out.append(("_bucket", k, (("le", "+Inf"),),
                            st["count"]))
                out.append(("_sum", k, (), st["sum"]))
                out.append(("_count", k, (), st["count"]))
        return out

    def snapshot_value(self, state):
        return {"buckets": dict(zip([_fmt(b) for b in self.buckets]
                                    + ["+Inf"], state["counts"])),
                "sum": round(state["sum"], 6), "count": state["count"]}


class MetricsRegistry:
    """Named metrics + weakly-held collectors; render on demand.

    ``counter``/``gauge``/``histogram`` create-or-return by name
    (re-registration with a different kind raises — one meaning per
    name).  ``register_collector(fn)`` adds a scrape-time callback
    ``fn() -> iterable of (name, kind, help, labels_dict, value)``
    sample tuples; a callback that raises ``ReferenceError`` or returns
    None is PRUNED (the weakref-death convention ``watch()`` uses).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._collectors = []

    # ------------------------------------------------------- creation

    def _named(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    "metric %r already registered as %s (wanted %s)"
                    % (name, m.kind, cls.kind))
            return m

    def counter(self, name, help="") -> Counter:
        return self._named(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:
        return self._named(Gauge, name, help)

    def histogram(self, name, help="",
                  buckets=LATENCY_BUCKETS_S) -> Histogram:
        return self._named(Histogram, name, help, buckets=buckets)

    def register_collector(self, fn) -> None:
        with self._lock:
            self._collectors.append(fn)

    def watch(self, obj, emit) -> None:
        """Register ``emit(obj) -> samples`` bound to a WEAK reference:
        once ``obj`` is collected the callback prunes itself from the
        next scrape (engines/routers are created per-test, per-bench —
        strong refs here would leak them all)."""
        ref = weakref.ref(obj)

        def _collect():
            o = ref()
            if o is None:
                return None          # prune
            return emit(o)
        self.register_collector(_collect)

    # ------------------------------------------------------ rendering

    def _collected(self) -> list:
        """Run the collectors (pruning dead ones); returns dynamic
        sample tuples (name, kind, help, labels, value)."""
        with self._lock:
            collectors = list(self._collectors)
        out, dead = [], []
        for fn in collectors:
            try:
                samples = fn()
            except ReferenceError:
                samples = None
            except Exception as e:   # a broken collector must never
                # break the scrape — but stays diagnosable
                from ..utils.profiling import note_swallowed
                note_swallowed("obs.metrics.collector", e)
                continue
            if samples is None:
                dead.append(fn)
                continue
            out.extend(samples)
        if dead:
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]
        return out

    def openmetrics(self) -> str:
        """The OpenMetrics/Prometheus text exposition of every static
        metric and collected sample, ``# EOF``-terminated."""
        lines = []
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        families = {}               # name -> (kind, help, [sample line])
        for m in metrics:
            rows = families.setdefault(m.name, (m.kind, m.help, []))[2]
            for suffix, key, extra, v in m.samples():
                rows.append("%s%s%s %s" % (m.name, suffix,
                                           _render_labels(key, extra),
                                           _fmt(v)))
        for name, kind, help, labels, v in self._collected():
            rows = families.setdefault(name, (kind, help, []))[2]
            key = _label_key(labels)
            if kind == "histogram":
                # v: {"buckets": [bounds], "counts": [n+1], "sum", "count"}
                acc = 0
                for b, c in zip(v["buckets"], v["counts"]):
                    acc += c
                    rows.append("%s_bucket%s %s" % (
                        name, _render_labels(key, (("le", _fmt(b)),)),
                        _fmt(acc)))
                rows.append("%s_bucket%s %s" % (
                    name, _render_labels(key, (("le", "+Inf"),)),
                    _fmt(v["count"])))
                rows.append("%s_sum%s %s" % (name, _render_labels(key),
                                             _fmt(v["sum"])))
                rows.append("%s_count%s %s" % (name, _render_labels(key),
                                               _fmt(v["count"])))
                continue
            suffix = "_total" if kind == "counter" else ""
            rows.append("%s%s%s %s" % (name, suffix, _render_labels(key),
                                       _fmt(v)))
        for name in sorted(families):
            kind, help, rows = families[name]
            if help:
                lines.append("# HELP %s %s" % (name, help))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-ready registry dump (benchmark records embed this)."""
        out = {}
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        for m in metrics:
            with m._lock:
                series = {(_render_labels(k) or "()"):
                          m.snapshot_value(v)
                          for k, v in sorted(m._values.items())}
            out[m.name] = {"kind": m.kind, "series": series}
        for name, kind, help, labels, v in self._collected():
            fam = out.setdefault(name, {"kind": kind, "series": {}})
            if isinstance(v, float):
                v = round(v, 6)
            fam["series"][_render_labels(_label_key(labels)) or "()"] = v
        json.dumps(out)              # must stay embeddable
        return out


#: the process registry everything self-registers into
REGISTRY = MetricsRegistry()

#: jax process_index label stamped on engine/router series (multi-host)
_PROCESS_INDEX: int | None = None


def set_process_index(index: int | None) -> None:
    """Stamp engine/router series with a ``process`` label — the
    ``jax.process_index()`` of this process.  ``multihost.initialize``
    calls this on success; cluster workers set their rank.  A scrape
    that merges per-host ``/metrics`` pages then stays attributable."""
    global _PROCESS_INDEX
    _PROCESS_INDEX = None if index is None else int(index)


def _with_process(labels: dict, override=None) -> dict:
    """Merge the process label into a sample's labels: an explicit
    per-object ``process_index`` (the cluster's simulated hosts) wins
    over the process-wide index; absent both, labels pass through."""
    p = override if override is not None else _PROCESS_INDEX
    if p is None or "process" in labels:
        return labels
    out = dict(labels)
    out["process"] = int(p)
    return out


def default_registry() -> MetricsRegistry:
    return REGISTRY


def observe_keygen(construction: str, batch: int, seconds: float,
                   registry: MetricsRegistry | None = None) -> None:
    """Record one batched-keygen call: keys produced and wall seconds,
    labeled by ``construction`` ("logn.r2" / "logn.r4" / "sqrtn.r2")
    and the batch size.  ``DPF.gen_batch`` calls this on every batch so
    keys/s per construction is derivable from any scrape
    (``dpf_keygen_keys_total / dpf_keygen_seconds_sum``).  Cheap and
    exception-free by the registry's create-or-return semantics."""
    reg = registry or REGISTRY
    labels = {"construction": str(construction), "batch": int(batch)}
    reg.counter(
        "dpf_keygen_keys",
        "DPF keys generated by batched keygen").labels(**labels).inc(
            int(batch))
    reg.counter(
        "dpf_keygen_batches",
        "Batched keygen calls").labels(**labels).inc()
    reg.histogram(
        "dpf_keygen_seconds",
        "Batched keygen wall time per call (s)").labels(
            **labels).observe(float(seconds))


# ----------------------------------------------- first-class exporters

#: EngineCounters fields exported per engine (counter semantics)
_ENGINE_COUNTER_FIELDS = (
    "batches_submitted", "queries_submitted", "dispatches",
    "padded_queries", "deadline_misses", "shed_batches", "shed_queries",
    "retries", "failovers", "breaker_opens", "engine_restarts",
    "swallowed_errors")
_ENGINE_TIME_FIELDS = ("pack_time_s", "dispatch_time_s", "wait_time_s")


def engine_samples(counters, labels: dict) -> list:
    """Sample tuples for one ``EngineCounters`` (shared by the
    per-engine watcher and the router's aggregate)."""
    out = []
    for f in _ENGINE_COUNTER_FIELDS:
        out.append(("dpf_engine_" + f, "counter",
                    "EngineCounters." + f, labels,
                    float(getattr(counters, f))))
    for f in _ENGINE_TIME_FIELDS:
        out.append(("dpf_engine_" + f.replace("_s", "_seconds"),
                    "counter", "EngineCounters." + f, labels,
                    float(getattr(counters, f))))
    out.append(("dpf_engine_in_flight_hwm", "gauge",
                "dispatch-window high-water mark", labels,
                float(counters.in_flight_hwm)))
    for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        v = counters.quantile(q)
        if v is not None:
            out.append(("dpf_engine_latency_%s_seconds" % name, "gauge",
                        "latency-ring nearest-rank quantile", labels, v))
    hist = getattr(counters, "latency_histogram", None)
    if callable(hist):
        h = hist()
        if h["count"]:
            out.append(("dpf_engine_latency_seconds", "histogram",
                        "per-batch submit->result latency "
                        "(fixed buckets; ring has exact quantiles)",
                        labels, h))
    return out


def _with_tenant(labels: dict, obj) -> dict:
    """Merge an object's ``tenant`` attribute into a sample's labels —
    the multi-tenant tier (serve/tenant.py) stamps routers, engines and
    breakers so every per-tenant series is filterable by ``tenant=``."""
    t = getattr(obj, "tenant", None)
    if t is None or "tenant" in labels:
        return labels
    out = dict(labels)
    out["tenant"] = str(t)
    return out


def register_engine(engine, registry: MetricsRegistry | None = None):
    """Export one engine's ``EngineCounters`` as
    ``dpf_engine_*{engine=...}`` series (weakly held; a ``tenant``
    attribute on the engine adds a ``tenant=`` label)."""
    reg = registry or REGISTRY
    label = getattr(engine, "label", None) or "engine-%x" % id(engine)

    def emit(e):
        return engine_samples(e.stats, _with_tenant(_with_process(
            {"engine": label}, getattr(e, "process_index", None)), e))
    reg.watch(engine, emit)


def register_router(router, registry: MetricsRegistry | None = None):
    """Export a ``SchemeRouter``'s breaker states, EWMA cost table and
    routing counts as first-class series (weakly held)."""
    reg = registry or REGISTRY
    states = {"closed": 0.0, "open": 1.0, "half_open": 2.0}

    def emit(r):
        out = []
        for lb, br in r.breakers.items():
            out.append(("dpf_breaker_state", "gauge",
                        "0=closed 1=open 2=half_open",
                        {"construction": lb}, states.get(br.state, -1.0)))
            out.append(("dpf_breaker_opens", "counter",
                        "closed->open transitions",
                        {"construction": lb}, float(br.opens)))
        kern_of = getattr(r, "dispatch_kernel", None)
        for (lb, bucket), s in sorted(r._costs.items()):
            labels = {"construction": lb, "bucket": bucket}
            if callable(kern_of):
                # label the estimate with the kernel the construction
                # would dispatch at this bucket (sqrtn: "xla" scan vs
                # "pallas" grid kernel) so a cost-table shift is
                # attributable to kernel selection
                kern = kern_of(lb, bucket)
                if kern is not None:
                    labels["kernel"] = kern
            out.append(("dpf_router_cost_seconds", "gauge",
                        "EWMA per-dispatch cost estimate", labels, s))
        for lb, c in r.route_counts.items():
            out.append(("dpf_router_routes", "counter",
                        "batches routed per construction",
                        {"construction": lb}, float(c)))
        for src, c in r.routed_from_counts.items():
            out.append(("dpf_router_routed_from", "counter",
                        "routing-decision provenance",
                        {"source": src}, float(c)))
        return [(n, k, h, _with_tenant(_with_process(l), r), v)
                for n, k, h, l, v in out]
    reg.watch(router, emit)


def register_cluster(cluster, registry: MetricsRegistry | None = None):
    """Export a ``parallel.cluster.ClusterRouter``'s host states, granule
    assignments, recovery decisions and cluster-merged ``EngineCounters``
    (``EngineCounters.merge`` pools the per-host rings) as first-class
    series (weakly held)."""
    reg = registry or REGISTRY
    states = {"live": 0.0, "degraded": 1.0, "down": 2.0}

    def emit(c):
        out = []
        for lb, node in c.hosts.items():
            st = c.host_state(lb)
            labels = _with_process({"host": lb},
                                   getattr(node, "process_index", None))
            out.append(("dpf_cluster_host_state", "gauge",
                        "0=live 1=degraded 2=down", labels,
                        states.get(st, -1.0)))
            out.append(("dpf_cluster_host_granules", "gauge",
                        "table granules assigned to the host", labels,
                        float(len(c.assignment.get(lb, ())))))
        live = sum(1 for lb in c.hosts if c.host_state(lb) == "live")
        out.append(("dpf_cluster_hosts_live", "gauge",
                    "hosts currently serving their own granules", {},
                    float(live)))
        out.append(("dpf_cluster_hosts_total", "gauge",
                    "hosts the cluster was built with", {},
                    float(len(c.hosts))))
        for decision in ("reshard", "degrade"):
            out.append(("dpf_cluster_recoveries", "counter",
                        "host-loss recovery decisions",
                        {"decision": decision},
                        float(c.decision_counts.get(decision, 0))))
        out.extend(engine_samples(c.counters(),
                                  _with_process({"engine": "cluster"})))
        return out
    reg.watch(cluster, emit)


def register_table_registry(registry_obj,
                            registry: MetricsRegistry | None = None):
    """Export a ``serve.registry.TableRegistry``'s residency state —
    budget/resident bytes, promotion/demotion/eviction counters and a
    per-(table, version) residency gauge — as ``dpf_registry_*`` series
    (weakly held)."""
    reg = registry or REGISTRY

    def emit(r):
        out = []
        st = r.stats()
        if st["budget_bytes"] is not None:
            out.append(("dpf_registry_budget_bytes", "gauge",
                        "configured device-residency byte budget", {},
                        float(st["budget_bytes"])))
        out.append(("dpf_registry_resident_bytes", "gauge",
                    "device bytes currently resident", {},
                    float(st["resident_bytes"])))
        for f in ("promotions", "demotions", "evictions",
                  "deferred_demotions", "hits", "misses",
                  "overcommits"):
            out.append(("dpf_registry_" + f, "counter",
                        "TableRegistry residency counter", {},
                        float(st["counters"][f])))
        for row in st["tables"]:
            out.append(("dpf_registry_table_resident", "gauge",
                        "1=device-resident 0=demoted to host RAM",
                        {"table": row["name"],
                         "version": row["version"]},
                        1.0 if row["resident"] else 0.0))
        return [(n, k, h, _with_process(l), v) for n, k, h, l, v in out]
    reg.watch(registry_obj, emit)


def register_granule_store(store_obj,
                           registry: MetricsRegistry | None = None):
    """Export a ``serve.registry.GranuleStore``'s granule-level
    residency state — the resident-granule gauge and the promotion/
    demotion/prefetch counters — as ``dpf_registry_granule*{store=...}``
    series (weakly held).  The granule-id detail rides the FLIGHT
    ``registry`` events (``granule=row0``); metrics carry the
    aggregate."""
    reg = registry or REGISTRY

    def emit(s):
        out = []
        st = s.stats()
        lbl = {"store": st["name"]}
        out.append(("dpf_registry_granules_resident", "gauge",
                    "granules currently device-resident", lbl,
                    float(st["granules_resident"])))
        out.append(("dpf_registry_granule_resident_bytes", "gauge",
                    "device bytes resident at granule grain", lbl,
                    float(st["resident_bytes"])))
        if st["budget_bytes"] is not None:
            out.append(("dpf_registry_granule_budget_bytes", "gauge",
                        "configured granule-residency byte budget", lbl,
                        float(st["budget_bytes"])))
        for f in ("promotions", "demotions", "evictions",
                  "deferred_demotions", "hits", "misses", "prefetches",
                  "prefetch_hits", "prefetch_misses", "overcommits"):
            out.append(("dpf_registry_granule_" + f, "counter",
                        "GranuleStore residency counter", lbl,
                        float(st["counters"][f])))
        return [(n, k, h, _with_process(l), v) for n, k, h, l, v in out]
    reg.watch(store_obj, emit)


def register_tenants(tenant_router,
                     registry: MetricsRegistry | None = None):
    """Export a ``serve.tenant.TenantRouter``'s scheduler state — queue
    depth, in-flight quota usage, DRR deficit, weight and the
    dispatch/shed counters — as ``dpf_tenant_*{tenant=...}`` series
    (weakly held).  The per-tenant ``SchemeRouter``s and engines
    self-register their own series with the ``tenant=`` label."""
    reg = registry or REGISTRY

    def emit(tr):
        out = []
        for name, ts in tr.tenants.items():
            labels = {"tenant": name}
            out.append(("dpf_tenant_weight", "gauge",
                        "weighted-fair scheduling weight", labels,
                        float(ts.spec.weight)))
            out.append(("dpf_tenant_queue_depth", "gauge",
                        "batches pending in the tenant queue", labels,
                        float(len(ts.queue))))
            out.append(("dpf_tenant_in_flight", "gauge",
                        "dispatched-but-unresolved batches", labels,
                        float(ts.in_flight)))
            out.append(("dpf_tenant_deficit", "gauge",
                        "deficit-round-robin credit (queries)", labels,
                        float(ts.deficit)))
            for f in ("submitted", "dispatched", "shed_batches",
                      "shed_queries", "quota_defers"):
                out.append(("dpf_tenant_" + f, "counter",
                            "tenant scheduler counter", labels,
                            float(getattr(ts, f))))
        return [(n, k, h, _with_process(l), v) for n, k, h, l, v in out]
    reg.watch(tenant_router, emit)


def register_planner(stats, registry: MetricsRegistry | None = None):
    """Export the planning tier's counters (``plan/twin.PLAN_STATS``,
    or any object with the same attribute surface) as ``dpf_plan_*``
    series (weakly held — the plan package owns the singleton, so the
    weakref stays live for the process lifetime).  The plan package is
    deliberately jax-free and never imports obs; the BENCH/planner
    process calls this after importing both sides."""
    reg = registry or REGISTRY

    def emit(s):
        out = []
        for f in ("twin_runs", "sim_arrivals", "sim_sheds", "sweeps",
                  "scale_ups", "scale_downs"):
            out.append(("dpf_plan_" + f, "counter",
                        "PlannerStats." + f, {},
                        float(getattr(s, f))))
        if s.last_p99_ms is not None:
            out.append(("dpf_plan_last_p99_ms", "gauge",
                        "p99 of the most recent twin run", {},
                        float(s.last_p99_ms)))
        if s.last_replicas is not None:
            out.append(("dpf_plan_last_replicas", "gauge",
                        "alive replicas at the end of the most recent "
                        "twin run", {}, float(s.last_replicas)))
        return [(n, k, h, _with_process(l), v) for n, k, h, l, v in out]
    reg.watch(stats, emit)


def _process_samples():
    """CacheCounters + SWALLOWED_ERRORS + tracer/flight meta — the
    process-wide series, registered once at import."""
    from ..utils.profiling import CACHE_COUNTERS, swallowed_snapshot
    out = []
    for f in ("tuning_hits", "tuning_misses", "tuning_stores",
              "compile_hits", "compile_misses"):
        out.append(("dpf_cache_" + f, "counter", "CacheCounters." + f,
                    {}, float(getattr(CACHE_COUNTERS, f))))
    out.append(("dpf_cache_compile_time_saved_seconds", "counter",
                "CacheCounters.compile_time_saved_s", {},
                float(CACHE_COUNTERS.compile_time_saved_s)))
    for site, by_cls in swallowed_snapshot().items():
        for cls, n in sorted(by_cls.items()):
            out.append(("dpf_swallowed_errors", "counter",
                        "note_swallowed registry",
                        {"site": site, "cls": cls}, float(n)))
    from . import tracer as _tracer
    t = _tracer.get_tracer()
    if t is not None:
        out.append(("dpf_trace_spans_recorded", "counter",
                    "spans landed in the tracer ring", {},
                    float(t.recorded)))
        out.append(("dpf_trace_spans_dropped", "counter",
                    "spans evicted from the full ring", {},
                    float(t.dropped)))
    from .flight import FLIGHT
    out.append(("dpf_flight_events", "counter",
                "events landed in the flight recorder", {},
                float(FLIGHT.recorded)))
    out.append(("dpf_flight_events_dropped", "counter",
                "events evicted from the full flight ring "
                "(widen with DPF_FLIGHT_RING)", {},
                float(getattr(FLIGHT, "dropped", 0))))
    return out


REGISTRY.register_collector(_process_samples)
