"""Multi-chip DPF evaluation: table row-sharding + batch sharding on a mesh.

The reference has no multi-GPU path at all (SURVEY.md §2.4); this module is
where the TPU build goes beyond it.  Two orthogonal parallel axes map the
workload onto a ``jax.sharding.Mesh``:

* **"table" axis (the TP analogue)** — the bit-reverse-permuted table is
  row-sharded; each chip owns a contiguous range of BFS leaf positions,
  i.e. a set of whole GGM frontier subtrees.  Every chip replicates the
  cheap phase-1 expansion (root -> frontier, O(B*F)), expands only its own
  subtrees, contracts against its local table rows, and the partial int32
  outputs are summed with ``psum`` over ICI.  Valid because additive secret
  shares commute with partial dot products.
* **"batch" axis (the DP analogue)** — independent DPF keys are embarrassingly
  parallel; the key batch is sharded and outputs concatenated.

Keys are ~2 KB each and broadcast over the mesh; output is [B, E] int32 —
both negligible next to the O(N) expansion, so scaling is linear in chips
until N/n_table_shards stops covering a chip.

All three constructions run sharded (binary GGM here, radix-4 via the
mixed engines, sqrt-N via ``core.sqrtn.eval_sharded_sqrt`` over a
natural-order table), the psum can be issued per chunk-group
(``psum_group`` — overlapping ICI latency with the next chunk's PRF
expansion), and ``ShardedDPFServer`` resolves its knobs from the
mesh-aware tuning cache (``tune/mesh_tune.py``).  See docs/SHARDING.md.

Multi-host runs use the same code: construct the mesh from
``jax.distributed``-initialized global devices and lay the "table" axis on
the ICI-adjacent dimension so psum rides ICI, not DCN.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import expand, u128
from ..core.expand import _level_step  # shared level recurrence

# jax.shard_map graduated from jax.experimental in newer releases;
# accept both so the mesh path runs on older jaxlibs too
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - version dependent
    from jax.experimental.shard_map import shard_map as _shard_map


def _pvary(x, axes):
    """Type a shard_map scan carry as varying over the mesh axes.  On
    jaxlibs without varying-types (no ``lax.pvary``) the carry mismatch
    this guards against does not exist — identity is correct.  Empty
    ``axes`` (a caller outside any shard_map, e.g. the cluster tier's
    host-local leaf-range eval) is always identity: ``lax.pvary`` over
    axis names that don't exist would raise."""
    fn = getattr(jax.lax, "pvary", None)
    return fn(x, axes) if fn is not None and axes else x


def make_mesh(n_table: int | None = None, n_batch: int = 1,
              devices=None) -> Mesh:
    """Build a ("batch", "table") mesh over the available devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_table is None:
        n_table = devices.size // n_batch
    assert n_table * n_batch == devices.size, \
        "mesh axes (%d x %d) must cover %d devices" % (
            n_batch, n_table, devices.size)
    return Mesh(devices.reshape(n_batch, n_table), ("batch", "table"))


def shard_table(table_i32: np.ndarray, mesh: Mesh):
    """Permute (bit-reversal) and row-shard a table over the "table" axis."""
    perm = expand.permute_table(np.asarray(table_i32, dtype=np.int32))
    sharding = NamedSharding(mesh, P("table", None))
    return jax.device_put(jnp.asarray(perm), sharding)


def _valid_psum_group(psum_group, n_chunks: int) -> int:
    """The effective chunk-group size for grouped psums: 0 (one terminal
    psum) unless ``psum_group`` divides the chunk count with at least
    two groups — a tuned value from another shape degrades to the
    terminal psum rather than failing the program."""
    g = int(psum_group or 0)
    return g if 0 < g < n_chunks and n_chunks % g == 0 else 0


def _scan_psum_groups(body, zeros, xs, axis_name: str,
                      outer_axes=("batch",)):
    """Grouped-psum driver shared by the three sharded constructions.

    Scans ``xs`` (every leaf already reshaped to ``[n_groups, g, ...]``)
    one chunk-group at a time: each group accumulates locally through
    ``body`` (a standard per-chunk scan body), the group partial is
    psummed over ``axis_name``, and the psum result adds onto the outer
    carry — int32 wrap keeps any grouping exact, and the collective has
    no data dependency on the NEXT group's PRF expansion, so an async
    backend overlaps ICI latency with compute.

    Carry typing: the INNER partial is varying over ``outer_axes`` plus
    ``axis_name`` (its body adds shard-local dot products), but the
    OUTER carry holds only psum outputs — invariant along ``axis_name``
    — so it is typed varying over ``outer_axes`` alone.  Typing it over
    the reduced axis too would trip shard_map's out_specs invariance
    check on jaxlibs with varying types (``lax.pvary`` present); on
    older jaxlibs both ``_pvary`` calls are identity.  The 2D row x
    entry-byte path passes ``outer_axes=("batch", "byte")``: its psum
    runs over "table" only, so the carry still varies over the byte
    axis (each byte shard holds a different entry block)."""
    def gbody(acc, xs_g):
        part0 = _pvary(zeros, tuple(outer_axes) + (axis_name,))
        part, _ = jax.lax.scan(body, part0, xs_g)
        return acc + jax.lax.psum(part, axis_name), None

    acc, _ = jax.lax.scan(gbody, _pvary(zeros, tuple(outer_axes)), xs)
    return acc


@functools.partial(jax.jit,
                   static_argnames=("depth", "prf_method", "chunk_leaves",
                                    "mesh", "aes_impl", "psum_group"))
def eval_sharded(cw1, cw2, last, table_perm, *, depth: int, prf_method: int,
                 chunk_leaves: int, mesh: Mesh, aes_impl: str | None = None,
                 psum_group: int = 0):
    """Mesh-parallel fused DPF evaluation.

    Inputs as in ``expand.expand_and_contract``; ``table_perm`` must be
    row-sharded with ``shard_table``.  ``psum_group`` > 0 accumulates
    the share psum per group of that many frontier-subtree chunks
    instead of once at the end — each group's collective has no data
    dependency on the next group's PRF expansion, so an async backend
    overlaps ICI latency with compute (int32 adds wrap: grouping cannot
    change the result).  Returns [B, E] int32 shares, replicated over
    the "table" axis and sharded over "batch".
    """
    n_shards = mesh.shape["table"]
    n = table_perm.shape[0]
    shard_rows = n // n_shards
    assert shard_rows * n_shards == n

    def per_shard(cw1, cw2, last, tbl_shard):
        # tbl_shard: [n/shards, E] — this chip's BFS leaf range
        shard_ix = jax.lax.axis_index("table")
        out, psummed = _eval_leaf_range(
            cw1, cw2, last, tbl_shard, shard_ix * shard_rows,
            depth=depth, prf_method=prf_method,
            chunk_leaves=min(chunk_leaves, shard_rows),
            n_total=n, aes_impl=aes_impl, psum_group=psum_group,
            axis_name="table")
        return out if psummed else jax.lax.psum(out, "table")

    fn = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("table", None)),
        out_specs=P("batch", None))
    return fn(cw1, cw2, last, table_perm)


def make_mesh_2d(n_table: int | None = None, n_byte: int = 1,
                 n_batch: int = 1, devices=None) -> Mesh:
    """Build a ("batch", "table", "byte") mesh: rows x entry-bytes over
    the host x chip grid.  ``n_byte=1`` degenerates to the 1D layout
    (and ``fingerprint.mesh_tag`` then emits the pre-2D tag, so tuned
    entries are shared).  Lay "table" on the ICI-adjacent dimension —
    the per-chunk psum rides it; the "byte" all_gather fires once per
    dispatch and tolerates the slower hops."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_table is None:
        n_table = devices.size // (n_batch * n_byte)
    assert n_table * n_batch * n_byte == devices.size, \
        "mesh axes (%d x %d x %d) must cover %d devices" % (
            n_batch, n_table, n_byte, devices.size)
    return Mesh(devices.reshape(n_batch, n_table, n_byte),
                ("batch", "table", "byte"))


def shard_table_2d(table_i32: np.ndarray, mesh: Mesh):
    """Permute (bit-reversal) and block-shard a table over the
    ("table", "byte") plane: each chip holds one ``[rows/n_table,
    E/n_byte]`` block — contiguous BFS leaf rows x a contiguous slice
    of entry columns (int32 words; "byte axis" names the role, the
    unit is the table's column dtype).  This is what lets a table
    larger than ONE chip's HBM spread over the whole grid: per-chip
    bytes shrink by n_table x n_byte."""
    perm = expand.permute_table(np.asarray(table_i32, dtype=np.int32))
    if perm.shape[1] % mesh.shape["byte"]:
        raise ValueError(
            "entry columns (%d) must divide over %d byte shards"
            % (perm.shape[1], mesh.shape["byte"]))
    sharding = NamedSharding(mesh, P("table", "byte"))
    return jax.device_put(jnp.asarray(perm), sharding)


@functools.partial(jax.jit,
                   static_argnames=("depth", "prf_method", "chunk_leaves",
                                    "mesh", "aes_impl", "psum_group"))
def eval_sharded_2d(cw1, cw2, last, table_perm, *, depth: int,
                    prf_method: int, chunk_leaves: int, mesh: Mesh,
                    aes_impl: str | None = None, psum_group: int = 0):
    """Mesh-parallel fused DPF evaluation over a 2D row x entry-byte
    table layout (``shard_table_2d``).

    Each chip expands only its row shard's GGM subtrees (the PRF work
    is replicated along the "byte" axis — byte shards of the same row
    range need the same leaf bits) and contracts them against its
    ``[rows_shard, e_shard]`` block.  Partials combine in a two-phase
    reduction: (1) psum over "table" — blocks in one byte column cover
    disjoint row ranges of the SAME entry columns, and additive int32
    shares commute with partial dot products, so the sum is exact; with
    ``psum_group`` the psum fires per chunk group and overlaps the next
    group's PRF expansion exactly like the 1D path (the grouped carry
    stays varying over "byte": ``_scan_psum_groups(outer_axes=("batch",
    "byte"))``).  (2) concatenation along "byte" — byte shards hold
    DIFFERENT entry columns, so they concatenate, they never sum; the
    concat is expressed as the OUTPUT LAYOUT (``out_specs=P("batch",
    "byte")``), which costs no collective at all: the global [B, E]
    result is simply sharded over "byte" on the entry axis (and
    replicated over "table"), and a consumer that needs it replicated
    pays the gather on materialization."""
    n_shards = mesh.shape["table"]
    n = table_perm.shape[0]
    shard_rows = n // n_shards
    assert shard_rows * n_shards == n

    def per_shard(cw1, cw2, last, tbl_block):
        # tbl_block: [n/n_table, E/n_byte] — this chip's 2D block
        shard_ix = jax.lax.axis_index("table")
        out, psummed = _eval_leaf_range(
            cw1, cw2, last, tbl_block, shard_ix * shard_rows,
            depth=depth, prf_method=prf_method,
            chunk_leaves=min(chunk_leaves, shard_rows),
            n_total=n, aes_impl=aes_impl, psum_group=psum_group,
            axis_name="table", carry_axes=("batch", "table", "byte"))
        if not psummed:
            out = jax.lax.psum(out, "table")
        return out

    fn = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("table", "byte")),
        out_specs=P("batch", "byte"))
    return fn(cw1, cw2, last, table_perm)


def _eval_leaf_range(cw1, cw2, last, tbl, row0, *, depth: int,
                     prf_method: int, chunk_leaves: int, n_total: int,
                     aes_impl: str | None = None, psum_group: int = 0,
                     axis_name: str | None = None,
                     carry_axes=("batch", "table")):
    """Expand only BFS leaves [row0, row0 + tbl.rows) and contract locally.

    Phase 1 walks root -> this shard's frontier; because the shard is a
    contiguous BFS range, its frontier nodes are a contiguous range at the
    frontier level, reachable by expanding all of phase 1 (cheap: width F)
    and slicing the local window with a dynamic slice on the node axis.

    Returns ``(out, psummed)``: with a valid ``psum_group`` (and an
    ``axis_name`` to reduce over) the scan psums every chunk group and
    ``out`` is already the mesh-wide sum (``psummed=True``); otherwise
    ``out`` is this shard's local partial and the caller applies the
    terminal psum.

    ``carry_axes`` types the scan carry for shard_map callers; pass
    ``()`` when calling OUTSIDE a mesh program (the multi-host cluster
    tier evaluates granules host-locally through exactly this path).
    """
    rows = tbl.shape[0]
    e = tbl.shape[1]
    bsz = last.shape[0]
    c = chunk_leaves
    f_local = rows // c                      # frontier nodes owned locally
    f_total = n_total // c                   # global frontier width
    f_levels = int(np.log2(f_total))

    seeds = last[:, None, :]
    for l in range(f_levels):
        seeds = _level_step(seeds, cw1, cw2, depth - 1 - l, prf_method,
                            aes_impl)
    # take the local frontier window [row0/c, row0/c + f_local)
    node0 = row0 // c
    seeds = jax.lax.dynamic_slice_in_dim(seeds, node0, f_local, axis=1)

    def expand_subtree(node_seeds):
        s = node_seeds[:, None, :]
        for l in range(f_levels, depth):
            s = _level_step(s, cw1, cw2, depth - 1 - l, prf_method,
                            aes_impl)
        return s[..., 0].astype(jnp.int32)

    tbl_chunks = tbl.reshape(f_local, c, e)
    if f_local == 1:
        return (expand._dot_i32(expand_subtree(seeds[:, 0, :]),
                                tbl_chunks[0]), False)

    frontier = jnp.moveaxis(seeds, 1, 0)  # [f_local, B, 4]

    def body(acc, xs):
        node_seeds, chunk = xs
        return acc + expand._dot_i32(expand_subtree(node_seeds), chunk), None

    zeros = jnp.zeros((bsz, e), dtype=jnp.int32)
    g = _valid_psum_group(psum_group, f_local) if axis_name else 0
    if not g:
        # inside shard_map the scan carry must be typed as varying over
        # the mesh axes (the body's output is), or the carry mismatches
        acc, _ = jax.lax.scan(body, _pvary(zeros, carry_axes),
                              (frontier, tbl_chunks))
        return acc, False
    return _scan_psum_groups(body, zeros, (
        frontier.reshape(f_local // g, g, bsz, 4),
        tbl_chunks.reshape(f_local // g, g, c, e)), axis_name,
        outer_axes=tuple(a for a in carry_axes if a != axis_name)), True


@functools.partial(jax.jit,
                   static_argnames=("depth", "prf_method", "chunk_leaves",
                                    "n_total", "aes_impl"))
def eval_leaf_range_local(cw1, cw2, last, tbl, row0, *, depth: int,
                          prf_method: int, chunk_leaves: int, n_total: int,
                          aes_impl: str | None = None):
    """Host-local partial evaluation of one contiguous BFS leaf range —
    the single-device (no-mesh) entry to ``_eval_leaf_range``.

    This is the multi-host cluster tier's per-host primitive
    (``parallel/cluster.py``): a host owning table rows
    [row0, row0 + tbl.rows) evaluates the FULL-domain key batch against
    only its rows and returns the [B, E] int32 partial share; partials
    from hosts covering disjoint ranges sum (int32 wrap) to the exact
    single-device answer, because additive secret shares commute with
    partial dot products.

    ``row0`` is a TRACED scalar (unlike the mesh path's
    ``shard_ix * shard_rows`` it arrives from the host), so one compiled
    program per (rows, batch) shape serves ANY granule — a re-shard
    after a host drop moves granules between hosts without recompiling.
    """
    out, _ = _eval_leaf_range(
        cw1, cw2, last, tbl, jnp.asarray(row0, dtype=jnp.int32),
        depth=depth, prf_method=prf_method, chunk_leaves=chunk_leaves,
        n_total=n_total, aes_impl=aes_impl, psum_group=0, axis_name=None,
        carry_axes=())
    return out


def shard_table_mixed(table_i32: np.ndarray, mesh: Mesh):
    """Digit-reverse-permute (radix-4 BFS order) and row-shard a table."""
    from ..core import radix4
    tbl = np.asarray(table_i32, dtype=np.int32)
    perm = radix4.mixed_reverse_indices(radix4.arities(tbl.shape[0]))
    sharding = NamedSharding(mesh, P("table", None))
    return jax.device_put(jnp.asarray(np.ascontiguousarray(tbl[perm])),
                          sharding)


def shard_table_sqrt(table_i32: np.ndarray, mesh: Mesh):
    """Row-shard a NATURAL-order table over the "table" axis for the
    sqrt-N construction (the grid emits natural order — no permutation):
    a contiguous N/shards row block is exactly R/shards whole grid rows
    for any key split whose R divides over the shards."""
    sharding = NamedSharding(mesh, P("table", None))
    return jax.device_put(
        jnp.asarray(np.asarray(table_i32, dtype=np.int32)), sharding)


@functools.partial(jax.jit,
                   static_argnames=("n", "prf_method", "chunk_leaves",
                                    "mesh", "aes_impl", "psum_group"))
def eval_sharded_mixed(cw1, cw2, last, table_perm, *, n: int,
                       prf_method: int, chunk_leaves: int, mesh: Mesh,
                       aes_impl: str | None = None, psum_group: int = 0):
    """Mesh-parallel radix-4 evaluation (the mixed-radix counterpart of
    ``eval_sharded``): each chip owns whole trailing radix-4 subtrees of
    the digit-reversed table, expands only those, psums partials —
    per ``psum_group`` chunks when set, terminally otherwise."""
    from ..core import radix4
    ars = radix4.arities(n)
    offs = radix4.cw_offsets(ars)
    n_shards = mesh.shape["table"]
    shard_rows = n // n_shards
    assert shard_rows * n_shards == n and shard_rows >= ars[-1]
    f_lv, c = radix4._suffix_chunk(ars, min(chunk_leaves, shard_rows))

    def _mixed_level(seeds, cw1_l, cw2_l, j):
        a = ars[j]
        return radix4._level_step_mixed(
            seeds, cw1_l[:, offs[j]:offs[j] + a, :],
            cw2_l[:, offs[j]:offs[j] + a, :], prf_method, a, aes_impl)

    def per_shard(cw1_l, cw2_l, last_l, tbl_shard):
        shard_ix = jax.lax.axis_index("table")
        rows = tbl_shard.shape[0]
        e = tbl_shard.shape[1]
        bsz = last_l.shape[0]
        f_local = rows // c

        seeds = last_l[:, None, :]
        for j in range(f_lv):
            seeds = _mixed_level(seeds, cw1_l, cw2_l, j)
        node0 = (shard_ix * rows) // c
        seeds = jax.lax.dynamic_slice_in_dim(seeds, node0, f_local, axis=1)

        def expand_subtree(node_seeds):
            s = node_seeds[:, None, :]
            for j in range(f_lv, len(ars)):
                s = _mixed_level(s, cw1_l, cw2_l, j)
            return s[..., 0].astype(jnp.int32)

        tbl_chunks = tbl_shard.reshape(f_local, c, e)
        if f_local == 1:
            out = expand._dot_i32(expand_subtree(seeds[:, 0, :]),
                                  tbl_chunks[0])
            return jax.lax.psum(out, "table")

        frontier = jnp.moveaxis(seeds, 1, 0)

        def body(acc, xs):
            node_seeds, chunk = xs
            return acc + expand._dot_i32(expand_subtree(node_seeds),
                                         chunk), None

        zeros = jnp.zeros((bsz, e), dtype=jnp.int32)
        g = _valid_psum_group(psum_group, f_local)
        if not g:
            out, _ = jax.lax.scan(body, _pvary(zeros, ("batch", "table")),
                                  (frontier, tbl_chunks))
            return jax.lax.psum(out, "table")
        return _scan_psum_groups(body, zeros, (
            frontier.reshape(f_local // g, g, bsz, 4),
            tbl_chunks.reshape(f_local // g, g, c, e)), "table")

    fn = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("table", None)),
        out_specs=P("batch", None))
    return fn(cw1, cw2, last, table_perm)


class ShardedDPFServer:
    """Convenience server wrapper: one table, mesh-parallel evaluation.

    The multi-chip counterpart of ``DPF.eval_init``/``eval_tpu``, for
    all three constructions: ``scheme="logn"`` (binary GGM, or the
    radix-4 tree with ``radix=4``), ``scheme="sqrtn"`` (natural-order
    table, ``sqrtn.eval_sharded_sqrt``), or ``scheme="auto"`` — the
    measured per-shape winner from the scheme tuning cache, resolved at
    construction exactly like ``DPF(scheme="auto")``
    (``scheme_resolved_from`` says which path answered).

    Knob resolution (``resolved_eval_knobs``) follows the DPF
    precedence per knob: an EXPLICIT value (ctor argument, or the
    matching attribute assigned afterwards) wins; auto (None) fields
    take the MESH-tuned entry for this device x mesh split
    (``tune.cache.lookup_mesh_knobs``, populated by ``benchmark.py
    --multichip``), then the single-device tuned entry, then the static
    per-shard heuristic (chunk choices clamp against the SHARD row
    count, not the full table — a tuned single-device chunk must not
    exceed a shard's leaf range).
    """

    def __init__(self, table, mesh: Mesh | None = None, prf_method: int = 3,
                 batch_size: int = 512, radix: int = 2,
                 scheme: str = "logn", chunk_leaves: int | None = None,
                 row_chunk: int | None = None,
                 psum_group: int | None = None,
                 dot_impl: str | None = None,
                 kernel_impl: str | None = None):
        from ..core import keygen  # local import to avoid cycles
        from ..utils.config import check_construction
        self._keygen = keygen
        self.mesh = mesh if mesh is not None else make_mesh()
        tbl = np.asarray(table, dtype=np.int32)
        self.n, self.entry_size = tbl.shape
        assert self.n & (self.n - 1) == 0
        check_construction(scheme, radix)
        self.scheme_resolved_from = None
        if scheme == "auto":
            if radix == 4:
                raise ValueError(
                    "scheme='auto' resolves the whole construction "
                    "(scheme AND radix) from the tuning cache; leave "
                    "radix at 2")
            scheme, radix = self._resolve_auto_scheme(batch_size,
                                                     prf_method)
        self.scheme = scheme
        self.radix = radix
        self.depth = self.n.bit_length() - 1
        self.prf_method = prf_method
        self.batch_size = batch_size
        n_shards = self.mesh.shape["table"]
        if self.n % n_shards:
            raise ValueError(
                "table rows (%d) must divide over %d table shards"
                % (self.n, n_shards))
        self.n_byte = dict(self.mesh.shape).get("byte", 1)
        if self.n_byte > 1 and (self.scheme != "logn" or self.radix != 2):
            raise ValueError(
                "byte-axis (2D) sharding serves the binary GGM "
                "construction only (scheme=%r radix=%d)"
                % (self.scheme, self.radix))
        if self.scheme == "sqrtn":
            self.table_sharded = shard_table_sqrt(tbl, self.mesh)
        elif self.radix == 4:
            self.table_sharded = shard_table_mixed(tbl, self.mesh)
        elif self.n_byte > 1:
            self.table_sharded = shard_table_2d(tbl, self.mesh)
        else:
            self.table_sharded = shard_table(tbl, self.mesh)
        # the explicit knob layer: ctor args (None = auto); assigning
        # these attributes afterwards pins the knob the same way
        self.chunk = chunk_leaves
        self.row_chunk = row_chunk
        self.psum_group = psum_group
        self.dot_impl = dot_impl
        self.kernel_impl = kernel_impl  # sqrtn: "xla" | "pallas" | None
        self._tuned_memo = {}  # batch -> (mesh-tuned, single-tuned) dicts

    def _resolve_auto_scheme(self, batch_size: int, prf_method: int):
        """scheme="auto" -> the concrete construction, the DPF way:
        scheme tuning cache first (the ``benchmark.py --autotune-scheme``
        winner for this shape on this machine), else the conservative
        cold-cache heuristic."""
        from ..tune.cache import lookup_scheme
        rec = lookup_scheme(n=self.n, entry_size=self.entry_size,
                            batch=batch_size, prf_method=prf_method)
        if rec and rec.get("scheme") in ("logn", "sqrtn"):
            self.scheme_resolved_from = "cache"
        else:
            from ..tune.search import heuristic_scheme
            rec = heuristic_scheme(self.n)
            self.scheme_resolved_from = "heuristic"
        return rec["scheme"], int(rec.get("radix") or 2)

    @property
    def shard_rows(self) -> int:
        """Table rows each "table"-axis shard owns."""
        return self.n // self.mesh.shape["table"]

    def _decode_batch(self, keys):
        """Vectorized ingest: wire keys -> packed batch validated
        against this server's table (shared with the serving engine)."""
        if not len(keys):
            raise ValueError("empty key batch")
        if self.scheme == "sqrtn":
            from ..core import sqrtn
            pk = sqrtn.decode_sqrt_keys_batched(keys)
        elif self.radix == 4:
            from ..core import radix4
            pk = radix4.decode_mixed_keys_batched(keys)
        else:
            pk = self._keygen.decode_keys_batched(keys)
        if pk.n != self.n:
            raise ValueError("key generated for n=%d but table has n=%d"
                             % (pk.n, self.n))
        return pk

    def resolved_eval_knobs(self, batch: int) -> dict:
        """Concrete mesh-program knobs for one dispatch batch size:
        explicit attribute > mesh-tuned (this device x mesh split,
        ``lookup_mesh_knobs``) > single-device tuned > heuristic.
        Chunk knobs resolve against the PER-SHARD row count (the shard
        owns ``shard_rows`` leaves / R/shards grid rows, not N).

        scheme='sqrtn': ``row_chunk`` may come back None — the dispatch
        resolves it against the decoded batch's key split
        (``sqrtn.clamp_row_chunk``), which only the keys know."""
        from ..ops import matmul128
        from ..tune.cache import lookup_eval_knobs, lookup_mesh_knobs
        from ..tune.fingerprint import mesh_tag
        explicit = {"chunk_leaves": self.chunk,
                    "row_chunk": self.row_chunk,
                    "psum_group": self.psum_group,
                    "dot_impl": self.dot_impl,
                    "kernel_impl": self.kernel_impl}
        fields = (("row_chunk", "psum_group", "dot_impl", "kernel_impl")
                  if self.scheme == "sqrtn"
                  else ("chunk_leaves", "psum_group", "dot_impl"))
        if all(explicit[f] is not None for f in fields):
            # fully pinned (the mesh tuner measuring a candidate): no
            # cache reads — a stale entry must not leak into the knobs
            tuned = single = {}
        else:
            # the cache lookups are memoized per batch (this is the
            # serving hot path); the process-global fallbacks below are
            # re-read every call so set_dot_impl stays live, matching
            # DPF.resolved_eval_knobs
            memo = self._tuned_memo.get(batch)
            if memo is None:
                memo = (lookup_mesh_knobs(
                            n=self.n, entry_size=self.entry_size,
                            batch=batch, prf_method=self.prf_method,
                            scheme=self.scheme, radix=self.radix,
                            mesh=mesh_tag(self.mesh)) or {},
                        lookup_eval_knobs(
                            n=self.n, entry_size=self.entry_size,
                            batch=batch, prf_method=self.prf_method,
                            scheme=self.scheme, radix=self.radix) or {})
                self._tuned_memo[batch] = memo
            tuned, single = memo

        def pick(field, fallback=None):
            if explicit[field] is not None:
                return explicit[field]
            v = tuned.get(field, single.get(field))
            return v if v is not None else fallback

        out = {"psum_group": int(pick("psum_group", 0) or 0),
               "dot_impl": pick("dot_impl", matmul128.default_impl())}
        if self.scheme == "sqrtn":
            out["row_chunk"] = pick("row_chunk")
            # kernel_impl with provenance, the DPF rule: explicit >
            # tuned > "xla"; a resolved "pallas" without Pallas/TPU
            # here degrades to the xla scan instead of raising
            if explicit["kernel_impl"] is not None:
                kernel, kernel_from = explicit["kernel_impl"], "config"
            elif tuned.get("kernel_impl",
                           single.get("kernel_impl")) is not None:
                kernel = tuned.get("kernel_impl",
                                   single.get("kernel_impl"))
                kernel_from = "tuned"
            else:
                kernel, kernel_from = "xla", "heuristic"
            if kernel == "pallas":
                from ..utils.compat import has_pallas_sqrt_kernel
                if not has_pallas_sqrt_kernel():
                    from ..utils.profiling import note_swallowed
                    note_swallowed(
                        "sharded.sqrt_kernel_unavailable",
                        RuntimeError(
                            "kernel_impl='pallas' (from %s) but Pallas/"
                            "TPU is unavailable here" % kernel_from))
                    kernel, kernel_from = "xla", "degraded"
            if (out["row_chunk"] is not None
                    and explicit["row_chunk"] is None
                    and (tuned.get("kernel_impl",
                                   single.get("kernel_impl", "xla"))
                         or "xla") != kernel):
                # a tuned row_chunk rides only with ITS kernel
                out["row_chunk"] = None
            out["kernel_impl"] = kernel
            out["kernel_resolved_from"] = kernel_from
            return out
        if explicit["chunk_leaves"] is not None:
            out["chunk_leaves"] = min(int(explicit["chunk_leaves"]),
                                      self.shard_rows)
        else:
            # clamp against the shard's own leaf range: tuned entries
            # (mesh or single-device) key on the table shape, and a
            # single-device chunk can exceed what one shard holds
            out["chunk_leaves"] = expand.clamp_chunk(
                tuned.get("chunk_leaves", single.get("chunk_leaves")),
                self.shard_rows, batch)
        return out

    def _dispatch_packed(self, pk):
        """Pad to the mesh "batch" axis and dispatch WITHOUT a host sync
        (async, for the serving engine's host/device overlap).  The
        returned device array may carry pad rows — callers trim to the
        real batch."""
        from ..core import prf as _prf
        pk = pk.pad_to(pk.batch
                       + (-pk.batch) % max(self.mesh.shape["batch"], 1))
        kn = self.resolved_eval_knobs(pk.batch)
        if self.scheme == "sqrtn":
            from ..core import sqrtn
            n_shards = self.mesh.shape["table"]
            if pk.n_codewords % n_shards:
                raise ValueError(
                    "sqrt-N key split R=%d does not divide over %d "
                    "table shards" % (pk.n_codewords, n_shards))
            rc = kn["row_chunk"]
            if self.row_chunk is None:
                # harden a tuned/absent row_chunk against THIS batch's
                # key split; an explicit pin passes through so an
                # invalid value raises instead of silently measuring
                # the heuristic (the DPF dispatch rule)
                rc = sqrtn.clamp_row_chunk(
                    rc, pk.n_codewords // n_shards, pk.n_keys, pk.batch)
            kernel = kn.get("kernel_impl", "xla")
            if kernel == "pallas":
                # the shape-level gate only the decoded batch answers:
                # per-SHARD rows must fit the grid kernel (blk prf ids
                # need R/shards % 4 == 0); degrade with provenance
                from ..ops.pallas_sqrt import pallas_sqrt_unsupported
                reason = pallas_sqrt_unsupported(
                    self.prf_method, pk.n_codewords // n_shards)
                if reason is not None:
                    from ..utils.profiling import note_swallowed
                    note_swallowed("sharded.sqrt_kernel_unsupported",
                                   ValueError(reason))
                    kernel = "xla"
            return sqrtn.eval_sharded_sqrt(
                pk.seeds, pk.cw1, pk.cw2, self.table_sharded,
                prf_method=self.prf_method, mesh=self.mesh,
                dot_impl=kn["dot_impl"], row_chunk=rc,
                psum_group=kn["psum_group"], kernel_impl=kernel)
        if self.radix == 4:
            return eval_sharded_mixed(
                pk.cw1, pk.cw2, pk.last, self.table_sharded, n=self.n,
                prf_method=self.prf_method,
                chunk_leaves=kn["chunk_leaves"], mesh=self.mesh,
                aes_impl=_prf._aes_pair_impl(),
                psum_group=kn["psum_group"])
        if self.n_byte > 1:
            return eval_sharded_2d(
                pk.cw1, pk.cw2, pk.last, self.table_sharded,
                depth=self.depth, prf_method=self.prf_method,
                chunk_leaves=kn["chunk_leaves"], mesh=self.mesh,
                aes_impl=_prf._aes_pair_impl(),
                psum_group=kn["psum_group"])
        return eval_sharded(pk.cw1, pk.cw2, pk.last, self.table_sharded,
                            depth=self.depth, prf_method=self.prf_method,
                            chunk_leaves=kn["chunk_leaves"],
                            mesh=self.mesh,
                            aes_impl=_prf._aes_pair_impl(),
                            psum_group=kn["psum_group"])

    def eval(self, keys) -> np.ndarray:
        pk = self._decode_batch(keys)
        return np.asarray(self._dispatch_packed(pk))[:pk.batch]

    def serving_engine(self, **kwargs):
        """Mesh-path ``ServingEngine`` (serve/engine.py) over this server."""
        from ..serve import ServingEngine
        return ServingEngine(self, **kwargs)
