"""Multi-chip DPF evaluation: table row-sharding + batch sharding on a mesh.

The reference has no multi-GPU path at all (SURVEY.md §2.4); this module is
where the TPU build goes beyond it.  Two orthogonal parallel axes map the
workload onto a ``jax.sharding.Mesh``:

* **"table" axis (the TP analogue)** — the bit-reverse-permuted table is
  row-sharded; each chip owns a contiguous range of BFS leaf positions,
  i.e. a set of whole GGM frontier subtrees.  Every chip replicates the
  cheap phase-1 expansion (root -> frontier, O(B*F)), expands only its own
  subtrees, contracts against its local table rows, and the partial int32
  outputs are summed with ``psum`` over ICI.  Valid because additive secret
  shares commute with partial dot products.
* **"batch" axis (the DP analogue)** — independent DPF keys are embarrassingly
  parallel; the key batch is sharded and outputs concatenated.

Keys are ~2 KB each and broadcast over the mesh; output is [B, E] int32 —
both negligible next to the O(N) expansion, so scaling is linear in chips
until N/n_table_shards stops covering a chip.

Multi-host runs use the same code: construct the mesh from
``jax.distributed``-initialized global devices and lay the "table" axis on
the ICI-adjacent dimension so psum rides ICI, not DCN.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import expand, u128
from ..core.expand import _level_step  # shared level recurrence

# jax.shard_map graduated from jax.experimental in newer releases;
# accept both so the mesh path runs on older jaxlibs too
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - version dependent
    from jax.experimental.shard_map import shard_map as _shard_map


def _pvary(x, axes):
    """Type a shard_map scan carry as varying over the mesh axes.  On
    jaxlibs without varying-types (no ``lax.pvary``) the carry mismatch
    this guards against does not exist — identity is correct."""
    fn = getattr(jax.lax, "pvary", None)
    return fn(x, axes) if fn is not None else x


def make_mesh(n_table: int | None = None, n_batch: int = 1,
              devices=None) -> Mesh:
    """Build a ("batch", "table") mesh over the available devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_table is None:
        n_table = devices.size // n_batch
    assert n_table * n_batch == devices.size, \
        "mesh axes (%d x %d) must cover %d devices" % (
            n_batch, n_table, devices.size)
    return Mesh(devices.reshape(n_batch, n_table), ("batch", "table"))


def shard_table(table_i32: np.ndarray, mesh: Mesh):
    """Permute (bit-reversal) and row-shard a table over the "table" axis."""
    perm = expand.permute_table(np.asarray(table_i32, dtype=np.int32))
    sharding = NamedSharding(mesh, P("table", None))
    return jax.device_put(jnp.asarray(perm), sharding)


@functools.partial(jax.jit,
                   static_argnames=("depth", "prf_method", "chunk_leaves",
                                    "mesh", "aes_impl"))
def eval_sharded(cw1, cw2, last, table_perm, *, depth: int, prf_method: int,
                 chunk_leaves: int, mesh: Mesh, aes_impl: str | None = None):
    """Mesh-parallel fused DPF evaluation.

    Inputs as in ``expand.expand_and_contract``; ``table_perm`` must be
    row-sharded with ``shard_table``.  Returns [B, E] int32 shares,
    replicated over the "table" axis and sharded over "batch".
    """
    n_shards = mesh.shape["table"]
    n = table_perm.shape[0]
    shard_rows = n // n_shards
    assert shard_rows * n_shards == n

    def per_shard(cw1, cw2, last, tbl_shard):
        # tbl_shard: [n/shards, E] — this chip's BFS leaf range
        shard_ix = jax.lax.axis_index("table")
        out = _eval_leaf_range(cw1, cw2, last, tbl_shard,
                               shard_ix * shard_rows,
                               depth=depth, prf_method=prf_method,
                               chunk_leaves=min(chunk_leaves, shard_rows),
                               n_total=n, aes_impl=aes_impl)
        return jax.lax.psum(out, "table")

    fn = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("table", None)),
        out_specs=P("batch", None))
    return fn(cw1, cw2, last, table_perm)


def _eval_leaf_range(cw1, cw2, last, tbl, row0, *, depth: int,
                     prf_method: int, chunk_leaves: int, n_total: int,
                     aes_impl: str | None = None):
    """Expand only BFS leaves [row0, row0 + tbl.rows) and contract locally.

    Phase 1 walks root -> this shard's frontier; because the shard is a
    contiguous BFS range, its frontier nodes are a contiguous range at the
    frontier level, reachable by expanding all of phase 1 (cheap: width F)
    and slicing the local window with a dynamic slice on the node axis.
    """
    rows = tbl.shape[0]
    e = tbl.shape[1]
    bsz = last.shape[0]
    c = chunk_leaves
    f_local = rows // c                      # frontier nodes owned locally
    f_total = n_total // c                   # global frontier width
    f_levels = int(np.log2(f_total))

    seeds = last[:, None, :]
    for l in range(f_levels):
        seeds = _level_step(seeds, cw1, cw2, depth - 1 - l, prf_method,
                            aes_impl)
    # take the local frontier window [row0/c, row0/c + f_local)
    node0 = row0 // c
    seeds = jax.lax.dynamic_slice_in_dim(seeds, node0, f_local, axis=1)

    def expand_subtree(node_seeds):
        s = node_seeds[:, None, :]
        for l in range(f_levels, depth):
            s = _level_step(s, cw1, cw2, depth - 1 - l, prf_method,
                            aes_impl)
        return s[..., 0].astype(jnp.int32)

    tbl_chunks = tbl.reshape(f_local, c, e)
    if f_local == 1:
        return expand._dot_i32(expand_subtree(seeds[:, 0, :]), tbl_chunks[0])

    frontier = jnp.moveaxis(seeds, 1, 0)  # [f_local, B, 4]

    def body(acc, xs):
        node_seeds, chunk = xs
        return acc + expand._dot_i32(expand_subtree(node_seeds), chunk), None

    acc0 = jnp.zeros((bsz, e), dtype=jnp.int32)
    # inside shard_map the scan carry must be typed as varying over the
    # mesh axes (the body's output is), or the carry types mismatch
    acc0 = _pvary(acc0, ("batch", "table"))
    acc, _ = jax.lax.scan(body, acc0, (frontier, tbl_chunks))
    return acc


def shard_table_mixed(table_i32: np.ndarray, mesh: Mesh):
    """Digit-reverse-permute (radix-4 BFS order) and row-shard a table."""
    from ..core import radix4
    tbl = np.asarray(table_i32, dtype=np.int32)
    perm = radix4.mixed_reverse_indices(radix4.arities(tbl.shape[0]))
    sharding = NamedSharding(mesh, P("table", None))
    return jax.device_put(jnp.asarray(np.ascontiguousarray(tbl[perm])),
                          sharding)


@functools.partial(jax.jit,
                   static_argnames=("n", "prf_method", "chunk_leaves",
                                    "mesh", "aes_impl"))
def eval_sharded_mixed(cw1, cw2, last, table_perm, *, n: int,
                       prf_method: int, chunk_leaves: int, mesh: Mesh,
                       aes_impl: str | None = None):
    """Mesh-parallel radix-4 evaluation (the mixed-radix counterpart of
    ``eval_sharded``): each chip owns whole trailing radix-4 subtrees of
    the digit-reversed table, expands only those, psums partials."""
    from ..core import radix4
    ars = radix4.arities(n)
    offs = radix4.cw_offsets(ars)
    n_shards = mesh.shape["table"]
    shard_rows = n // n_shards
    assert shard_rows * n_shards == n and shard_rows >= ars[-1]
    f_lv, c = radix4._suffix_chunk(ars, min(chunk_leaves, shard_rows))

    def _mixed_level(seeds, cw1_l, cw2_l, j):
        a = ars[j]
        return radix4._level_step_mixed(
            seeds, cw1_l[:, offs[j]:offs[j] + a, :],
            cw2_l[:, offs[j]:offs[j] + a, :], prf_method, a, aes_impl)

    def per_shard(cw1_l, cw2_l, last_l, tbl_shard):
        shard_ix = jax.lax.axis_index("table")
        rows = tbl_shard.shape[0]
        e = tbl_shard.shape[1]
        bsz = last_l.shape[0]
        f_local = rows // c

        seeds = last_l[:, None, :]
        for j in range(f_lv):
            seeds = _mixed_level(seeds, cw1_l, cw2_l, j)
        node0 = (shard_ix * rows) // c
        seeds = jax.lax.dynamic_slice_in_dim(seeds, node0, f_local, axis=1)

        def expand_subtree(node_seeds):
            s = node_seeds[:, None, :]
            for j in range(f_lv, len(ars)):
                s = _mixed_level(s, cw1_l, cw2_l, j)
            return s[..., 0].astype(jnp.int32)

        tbl_chunks = tbl_shard.reshape(f_local, c, e)
        if f_local == 1:
            out = expand._dot_i32(expand_subtree(seeds[:, 0, :]),
                                  tbl_chunks[0])
        else:
            frontier = jnp.moveaxis(seeds, 1, 0)

            def body(acc, xs):
                node_seeds, chunk = xs
                return acc + expand._dot_i32(expand_subtree(node_seeds),
                                             chunk), None

            acc0 = jnp.zeros((bsz, e), dtype=jnp.int32)
            acc0 = _pvary(acc0, ("batch", "table"))
            out, _ = jax.lax.scan(body, acc0, (frontier, tbl_chunks))
        return jax.lax.psum(out, "table")

    fn = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("batch"), P("batch"), P("batch"), P("table", None)),
        out_specs=P("batch", None))
    return fn(cw1, cw2, last, table_perm)


class ShardedDPFServer:
    """Convenience server wrapper: one table, mesh-parallel evaluation.

    The multi-chip counterpart of ``DPF.eval_init``/``eval_tpu``.
    """

    def __init__(self, table, mesh: Mesh | None = None, prf_method: int = 3,
                 batch_size: int = 512, radix: int = 2):
        from ..core import keygen  # local import to avoid cycles
        self._keygen = keygen
        self.mesh = mesh if mesh is not None else make_mesh()
        tbl = np.asarray(table, dtype=np.int32)
        self.n, self.entry_size = tbl.shape
        assert self.n & (self.n - 1) == 0
        assert radix in (2, 4)
        self.radix = radix
        self.depth = self.n.bit_length() - 1
        self.prf_method = prf_method
        self.batch_size = batch_size
        if radix == 4:
            self.table_sharded = shard_table_mixed(tbl, self.mesh)
        else:
            self.table_sharded = shard_table(tbl, self.mesh)
        shard_rows = self.n // self.mesh.shape["table"]
        # tuned chunk_leaves (persistent tuning cache, keyed by device
        # fingerprint x shape) when one exists for this shape, else the
        # static heuristic — capped at the shard height either way
        from ..tune.cache import lookup_eval_knobs
        tuned = lookup_eval_knobs(
            n=self.n, entry_size=self.entry_size, batch=batch_size,
            prf_method=prf_method, scheme="logn", radix=radix) or {}
        self.chunk = min(expand.clamp_chunk(tuned.get("chunk_leaves"),
                                            self.n, batch_size),
                         shard_rows)

    def _decode_batch(self, keys):
        """Vectorized ingest: wire keys -> PackedKeys validated against
        this server's table (shared with the serving engine)."""
        if not len(keys):
            raise ValueError("empty key batch")
        if self.radix == 4:
            from ..core import radix4
            pk = radix4.decode_mixed_keys_batched(keys)
        else:
            pk = self._keygen.decode_keys_batched(keys)
        if pk.n != self.n:
            raise ValueError("key generated for n=%d but table has n=%d"
                             % (pk.n, self.n))
        return pk

    def _dispatch_packed(self, pk):
        """Pad to the mesh "batch" axis and dispatch WITHOUT a host sync
        (async, for the serving engine's host/device overlap).  The
        returned device array may carry pad rows — callers trim to the
        real batch."""
        from ..core import prf as _prf
        pk = pk.pad_to(pk.batch
                       + (-pk.batch) % max(self.mesh.shape["batch"], 1))
        if self.radix == 4:
            return eval_sharded_mixed(
                pk.cw1, pk.cw2, pk.last, self.table_sharded, n=self.n,
                prf_method=self.prf_method, chunk_leaves=self.chunk,
                mesh=self.mesh, aes_impl=_prf._aes_pair_impl())
        return eval_sharded(pk.cw1, pk.cw2, pk.last, self.table_sharded,
                            depth=self.depth, prf_method=self.prf_method,
                            chunk_leaves=self.chunk, mesh=self.mesh,
                            aes_impl=_prf._aes_pair_impl())

    def eval(self, keys) -> np.ndarray:
        pk = self._decode_batch(keys)
        return np.asarray(self._dispatch_packed(pk))[:pk.batch]

    def resolved_eval_knobs(self, batch: int) -> dict:
        """The mesh path's effective program knobs (for benchmark
        records — serve/engine.py ``resolved_config``)."""
        from ..ops import matmul128
        return {"chunk_leaves": self.chunk,
                "dot_impl": matmul128.default_impl()}

    def serving_engine(self, **kwargs):
        """Mesh-path ``ServingEngine`` (serve/engine.py) over this server."""
        from ..serve import ServingEngine
        return ServingEngine(self, **kwargs)
