"""Multi-host serving cluster: row-sharded table, scatter/gather
front-end, and a host-loss recovery state machine.

``parallel/sharded.py`` scales one *process* over its local mesh;
``parallel/multihost.py`` initializes jax.distributed so many processes
form one global mesh.  This module is the missing serving tier between
them: a **cluster** of serving hosts, each owning a slice of the table,
behind one front-end router — and a failure story when a host dies
mid-trace.

**Sharding model.**  The bit-reverse-permuted table splits into
``hosts`` contiguous **granules** of ``granule = n // hosts`` rows.
Each host wraps its granules in a ``ClusterShardServer`` whose
``_dispatch_packed`` runs ``sharded.eval_leaf_range_local`` per granule
— the *partial* DPF evaluation over just those rows — and sums the
partials on device.  Because answers are additive int32 shares, partial
dot products over disjoint row ranges sum (wrapping) to exactly the
full-table answer; the front-end ``ClusterRouter`` scatters each batch
to every covering host and merges the returned partials with a wrapping
sum, bit-identical to a single-host eval (tests/test_cluster.py gates
this against ``DPF.eval_cpu``).  ``row0`` is *traced*, so ONE compiled
program per (granule, bucket) shape serves ANY granule — recovery moves
granules between hosts without recompiling.

**Failure story.**  Losses are detected three ways: a dispatch raising
``HostDropped``/``EngineDead`` (serve/faults.py injects these under the
``host_drop`` kind), a failed heartbeat (``check_hosts`` consults
``FaultInjector.on_heartbeat``), or a per-host ``CircuitBreaker``
opening after K consecutive transient failures.  All three converge on
``_handle_drop``, which takes the host out of the scatter plan and
answers the loss with one of two decisions (``policy=``):

* ``"reshard"`` — the dead host's granules are redistributed
  round-robin over the survivors (``add_granules`` = one ``device_put``
  each; the traced-``row0`` program is already compiled), restoring
  full replication-free coverage.
* ``"degrade"``  — a front-end **spare** ``LocalHost`` takes over the
  dead granules from the router's retained permuted table: partial
  availability served locally while the dead host stays excluded.
* ``"auto"``     — reshard when survivors exist, else degrade.

Every decision lands in the flight recorder (``host_drop`` then
``cluster_recovery`` with ``decision``), counts in ``decision_counts``,
and moves the cluster-level ``EngineCounters`` (reshard ->
``engine_restarts``, degrade -> ``failovers``) — the chaos bench
(``benchmark.py --multihost``) asserts the attribution chain end to
end.  ``obs.metrics.register_cluster`` exports host states, granule
assignments and recovery decisions as first-class series.

Hosts are pluggable: ``LocalHost`` (in-process, the simulation tier
that runs everywhere) and ``cluster_net.RemoteHost`` (a socket client
for ``cluster_worker`` processes) implement the same five-method
protocol, so the router is transport-agnostic.
"""

from __future__ import annotations

import threading

import numpy as np

from ..core import expand, keygen
from ..core.expand import DeadlineExceeded
from ..obs.flight import FLIGHT
from ..serve.engine import LoadShed, ServingEngine
from ..serve.faults import CircuitBreaker, EngineDead, HostDropped
from ..utils.profiling import EngineCounters, note_swallowed

#: recovery decisions a policy can produce
DECISIONS = ("reshard", "degrade")


class HostUnreachable(RuntimeError):
    """A serving host stopped answering (socket death, worker exit, or a
    poisoned engine observed mid-submit).  The router treats it like
    ``HostDropped``: exclusion + recovery, then a resubmit."""


class ClusterUnavailable(RuntimeError):
    """The live hosts (plus spare) no longer cover the whole table —
    recovery failed or every host is down.  Answers would be WRONG
    shares, so the router refuses to serve instead."""


# ------------------------------------------------------------- planning

def granule_rows(n: int, hosts: int) -> int:
    """Rows per granule for an ``n``-row table over ``hosts`` hosts.

    Both must be powers of two (the BFS leaf order and the chunked
    expansion kernel require pow2 row counts), hosts <= n."""
    if hosts < 1 or (hosts & (hosts - 1)):
        raise ValueError("hosts must be a power of two >= 1 (got %d)"
                         % hosts)
    if n % hosts:
        raise ValueError("hosts (%d) must divide n (%d)" % (hosts, n))
    g = n // hosts
    if g & (g - 1):
        raise ValueError("granule %d is not a power of two (n=%d)"
                         % (g, n))
    return g


def make_plan(n: int, hosts: int) -> dict:
    """Initial granule assignment: host i owns rows [i*g, (i+1)*g) of
    the PERMUTED table.  Returns {label: (row0, ...)} with labels
    "host0".."host<H-1>" — the labels fault specs target."""
    g = granule_rows(n, hosts)
    return {"host%d" % i: (i * g,) for i in range(hosts)}


def reshard_plan(lost, survivors) -> dict:
    """Distribute ``lost`` granule row0s round-robin over ``survivors``
    (ordered labels).  Returns {label: (row0, ...)} of ADDITIONS."""
    if not survivors:
        raise ValueError("no survivors to reshard onto")
    out = {lb: [] for lb in survivors}
    for i, row0 in enumerate(sorted(lost)):
        out[survivors[i % len(survivors)]].append(row0)
    return {lb: tuple(v) for lb, v in out.items() if v}


# ---------------------------------------------------------- shard server

class ClusterShardServer:
    """One host's table slice behind the ``ServingEngine`` server
    protocol (``_decode_batch`` / ``_dispatch_packed``).

    Holds a list of (row0, device granule) shards over the bit-reverse
    PERMUTED table; a dispatch evaluates each granule's partial share
    via ``sharded.eval_leaf_range_local`` (traced row0 — one program
    per (granule, bucket) shape regardless of which granules this host
    holds) and sums the partials on device, still async.
    ``add_granules`` is the recovery hook: a ``device_put`` per new
    granule, no recompilation.

    ``budget_bytes`` switches the host to PAGED residency (the
    big-table tier): granules live in a ``serve.registry.GranuleStore``
    instead of pinned device buffers, so the host can be ASSIGNED more
    table bytes than its device budget holds.  A dispatch then walks
    its assignment leasing each granule (demand-promoting cold ones
    through the same ``device_put`` path — bit-identical bytes), and
    issues a free-budget prefetch of the NEXT granule before each
    eval so page-in overlaps the in-flight async compute.  Recovery is
    unchanged: ``add_granules`` on a paged host just extends the
    assignment — faulted-in granules page up on first dispatch.
    """

    scheme = "logn"

    def __init__(self, table_perm: np.ndarray, row0s, granule: int, *,
                 prf_method: int, batch_size: int = 512,
                 aes_impl: str | None = None,
                 budget_bytes: int | None = None):
        import jax.numpy as jnp
        if table_perm.ndim != 2:
            raise ValueError("table_perm must be [n, entry_size]")
        self._jnp = jnp
        self._table_perm = table_perm          # shared ref, host memory
        self.n = int(table_perm.shape[0])
        self.entry_size = int(table_perm.shape[1])
        self.granule = int(granule)
        self.prf_method = int(prf_method)
        self.batch_size = int(batch_size)
        self.aes_impl = aes_impl
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self._shards = []                      # [(row0, device [g, E])]
        self._assigned = []                    # paged mode: row0 list
        self.store = None                      # paged mode: GranuleStore
        if self.budget_bytes is not None:
            from ..serve.registry import GranuleStore
            self.store = GranuleStore(table_perm, self.granule,
                                      budget_bytes=self.budget_bytes)
        self.add_granules(row0s)

    @property
    def paged(self) -> bool:
        return self.store is not None

    def add_granules(self, row0s) -> None:
        """Upload granules [row0, row0+granule) (recovery/reshard
        entry point — device transfer only, the jitted program for this
        granule shape is shared with every other granule).  On a paged
        host this only extends the ASSIGNMENT: the granule pages up at
        its first dispatch (or prefetch) instead of eagerly, so a
        recovery reshard never blows the device budget."""
        import jax
        held = (set(self._assigned) if self.paged
                else {r for r, _ in self._shards})
        for row0 in row0s:
            row0 = int(row0)
            if row0 % self.granule or not 0 <= row0 < self.n:
                raise ValueError("row0 %d not a granule boundary (g=%d)"
                                 % (row0, self.granule))
            if row0 in held:
                continue
            if self.paged:
                self._assigned.append(row0)
            else:
                sl = self._table_perm[row0:row0 + self.granule]
                self._shards.append((row0, jax.device_put(sl)))
            held.add(row0)
        self._shards.sort(key=lambda t: t[0])
        self._assigned.sort()

    def set_granules(self, row0s) -> None:
        """Replace the held granules wholesale (hot-standby promotion:
        the placeholder granule the standby warmed up on swaps for the
        dead host's real granules — same traced shape, so still no
        recompilation)."""
        self._shards = []
        if self.paged:
            self._assigned = []
            self.store.demote_all()
        self.add_granules(row0s)

    @property
    def granules(self) -> tuple:
        if self.paged:
            return tuple(self._assigned)
        return tuple(r for r, _ in self._shards)

    def _decode_batch(self, keys) -> keygen.PackedKeys:
        if isinstance(keys, keygen.PackedKeys):
            pk = keys                          # front-end decoded once
        else:
            pk = keygen.decode_keys_batched(keys)
        if pk.n != self.n:
            raise ValueError("keys for n=%d but table has n=%d"
                             % (pk.n, self.n))
        return pk

    def _dispatch_packed(self, pk: keygen.PackedKeys):
        """Sum of this host's granule partials ([B, E] int32, device,
        async).  Wrapping int32 adds keep additive-share semantics.

        Paged mode walks the assignment in row0 order: lease (fault-in
        when cold), dispatch the async partial eval, release, then
        prefetch the NEXT granule into free budget — the page-in
        ``device_put`` runs while the just-dispatched eval is still in
        flight, which is the overlap that keeps paging off the
        critical path."""
        from . import sharded
        if not (self._assigned if self.paged else self._shards):
            raise RuntimeError("shard server holds no granules")
        chunk = expand.clamp_chunk(0, self.granule, pk.batch)

        def eval_one(row0, tbl, out):
            part = sharded.eval_leaf_range_local(
                pk.cw1, pk.cw2, pk.last, tbl, row0, depth=pk.depth,
                prf_method=self.prf_method, chunk_leaves=chunk,
                n_total=self.n, aes_impl=self.aes_impl)
            return part if out is None else self._jnp.add(out, part)

        if self.paged:
            out = None
            for i, row0 in enumerate(self._assigned):
                lease = self.store.lease(row0)
                try:
                    out = eval_one(row0, lease.table, out)
                finally:
                    lease.release()
                if i + 1 < len(self._assigned):
                    self.store.prefetch(self._assigned[i + 1])
            return out
        out = None
        for row0, tbl in self._shards:
            out = eval_one(row0, tbl, out)
        return out


# --------------------------------------------------------------- hosts

class LocalHost:
    """In-process serving host: a ``ClusterShardServer`` behind a
    ``ServingEngine`` labeled with the host name (fault specs target
    that label).  The simulation tier — and the node protocol
    (``submit``/``heartbeat``/``add_granules``/``counters``/``stats``)
    ``cluster_net.RemoteHost`` mirrors over sockets."""

    def __init__(self, label: str, server: ClusterShardServer, *,
                 process_index: int | None = None, buckets=None,
                 injector=None, **engine_kw):
        self.label = label
        self.process_index = process_index
        self.server = server
        self._injector = injector
        self.engine = ServingEngine(server, buckets=buckets, label=label,
                                    injector=injector, **engine_kw)

    def submit(self, pk):
        return self.engine.submit(pk)

    def heartbeat(self) -> dict:
        """Liveness probe; raises ``HostDropped`` when this host is
        (injected-)dead.  Returns a tiny status dict otherwise."""
        if self._injector is not None:
            self._injector.on_heartbeat(self.engine)
        return {"host": self.label, "granules": self.server.granules,
                "in_flight": self.engine.in_flight}

    def add_granules(self, row0s) -> None:
        self.server.add_granules(row0s)

    @property
    def granules(self) -> tuple:
        return self.server.granules

    def counters(self) -> EngineCounters:
        return self.engine.stats

    def stats(self) -> dict:
        return {"granules": list(self.server.granules),
                "counters": self.engine.stats.as_dict()}

    def warmup(self) -> None:
        self.engine.warmup()

    def drain(self) -> None:
        self.engine.drain()

    def close(self) -> None:
        pass


# -------------------------------------------------------------- future

class ClusterFuture:
    """Merged result handle for one scattered batch.

    ``result()`` gathers every host's partial share and merges them
    with a wrapping int32 sum.  A host loss observed while gathering
    (``HostDropped``/``EngineDead``/``HostUnreachable``) runs the
    recovery state machine and RE-SERVES the whole batch on the
    recovered cluster — bounded by the router's ``max_retries`` — so a
    caller sees either a correct merged share or the terminal error.
    """

    def __init__(self, router, pk, parts):
        self._router = router
        self._pk = pk
        self._parts = parts          # [(label, engine future)]
        self._value = None

    def done(self) -> bool:
        return self._value is not None

    def result(self):
        if self._value is not None:
            return self._value
        r = self._router
        parts, attempt = self._parts, 0
        while True:
            try:
                self._value = r._merge(self._gather(parts))
                return self._value
            except (HostDropped, EngineDead, HostUnreachable):
                attempt += 1
                if attempt > r.max_retries:
                    raise
                parts = r._scatter(self._pk)   # recovered coverage

    def _gather(self, parts):
        out = []
        for lb, fut in parts:
            try:
                out.append(fut.result())
                self._router._note_ok(lb)
            except (LoadShed, DeadlineExceeded):
                raise                # decisions, not faults — propagate
            except (HostDropped, EngineDead, HostUnreachable) as e:
                self._router._handle_drop(lb, e)
                raise
            except Exception as e:
                if self._router._note_failure(lb, e):
                    raise HostUnreachable(
                        "host %r breaker opened: %s" % (lb, e)) from e
                raise
        return out


# -------------------------------------------------------------- router

class ClusterRouter:
    """Scatter/gather front-end over a set of serving hosts.

    Args:
      nodes: host-protocol objects (``LocalHost``/``RemoteHost``),
        labels unique.
      granule: rows per granule (``granule_rows(n, hosts)``).
      table_perm: the full PERMUTED table (host memory).  Required for
        the ``degrade`` path (the front-end spare serves the dead
        granules from it); ``None`` restricts recovery to ``reshard``.
      policy: ``"reshard"`` | ``"degrade"`` | ``"auto"`` (reshard when
        survivors exist, else degrade).
      injector: ``faults.FaultInjector`` — heartbeats consult
        ``on_heartbeat`` through each node; the engines already consult
        the dispatch/result points.
      breaker_failures/breaker_reset_s: per-host circuit breakers; a
        breaker *opening* is treated as a host loss (the open callback
        runs ``_handle_drop``), which is exactly "the breaker keeps the
        dead host out of the scatter plan".
      max_retries: whole-batch re-serves a ``ClusterFuture`` may attempt
        after recoveries.
      standby: pre-build and warm the front-end spare at construction
        time (on a placeholder granule — row0 is traced, so the same
        compiled programs serve whichever granules later die).  A
        ``degrade`` failover then costs one ``device_put`` swap instead
        of a jit compile inside the recovery window.  Matters most when
        the front-end process never served (multiprocess clusters,
        where the workers hold the compile caches).

    ``hosts``/``assignment``/``host_state``/``decision_counts``/
    ``counters`` form the observability surface
    ``obs.metrics.register_cluster`` exports.
    """

    def __init__(self, nodes, *, granule: int, table_perm=None,
                 policy: str = "auto", injector=None,
                 breaker_failures: int = 3, breaker_reset_s: float = 30.0,
                 max_retries: int = 2, spare_engine_kw=None,
                 prf_method: int | None = None, standby: bool = False):
        if policy not in DECISIONS + ("auto",):
            raise ValueError("policy must be reshard|degrade|auto "
                             "(got %r)" % (policy,))
        self.hosts = {node.label: node for node in nodes}
        if len(self.hosts) != len(list(nodes)):
            raise ValueError("duplicate host labels")
        self.granule = int(granule)
        self._table_perm = table_perm
        self.policy = policy
        self.injector = injector
        self.max_retries = int(max_retries)
        self._spare_engine_kw = dict(spare_engine_kw or {})
        first = next(iter(self.hosts.values()))
        self.n = first.server.n if hasattr(first, "server") else first.n
        if prf_method is None:  # remote nodes carry no server object
            srv = getattr(first, "server", None)
            prf_method = getattr(srv, "prf_method", None)
        self._prf_method = prf_method
        self._all_granules = frozenset(range(0, self.n, self.granule))
        self._assign = {lb: tuple(node.granules)
                        for lb, node in self.hosts.items()}
        self._down = set()
        self._lock = threading.RLock()
        self.spare = None
        self.recovery = EngineCounters()
        self.decision_counts = {d: 0 for d in DECISIONS}
        self.breakers = {
            lb: CircuitBreaker(failures=breaker_failures,
                               reset_s=breaker_reset_s, name=lb,
                               on_open=self._on_breaker_open)
            for lb in self.hosts}
        covered = set()
        for g in self._assign.values():
            covered.update(g)
        if covered != set(self._all_granules):
            raise ValueError("initial assignment does not tile the "
                             "table: missing %s"
                             % sorted(self._all_granules - covered))
        if standby:
            # hot standby: compile the spare's programs NOW, while the
            # cluster is healthy, on a placeholder granule; _degrade
            # promotes it with a set_granules swap (no recompiles)
            self.spare = self._build_spare((0,))
        try:
            from ..obs.metrics import register_cluster
            register_cluster(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("cluster.register_metrics", e, self.recovery)

    # ------------------------------------------------------ construction

    @classmethod
    def local(cls, table, hosts: int = 2, *, prf_method=None,
              oracle=None, buckets=None, injector=None,
              engine_kw=None, host_budget_bytes=None,
              **router_kw) -> "ClusterRouter":
        """Build an all-in-process cluster over ``table`` — the
        simulation tier (tests, the ``--multihost`` bench's fallback
        mode) exercising the identical scatter/recovery state machine
        the multiprocess tier runs.

        ``oracle`` (an ``api.DPF``) supplies ``prf_method`` when not
        given explicitly; consults the tuning cache for cluster scatter
        knobs (bucket ladder / in-flight window) unless ``buckets``
        pins them.  ``host_budget_bytes`` builds every host PAGED
        (granule-level residency bounded to that device budget — the
        big-table tier, where a host's assignment may exceed what its
        device holds).
        """
        if prf_method is None:
            if oracle is not None:
                prf_method = oracle.prf_method
            else:
                from ..api import DPF
                prf_method = DPF.DEFAULT_PRF
        tbl = np.ascontiguousarray(np.asarray(table, dtype=np.int32))
        n = tbl.shape[0]
        g = granule_rows(n, hosts)
        perm = expand.permute_table(tbl)
        kw = dict(engine_kw or {})
        if buckets is None:
            try:
                from ..tune.serve_tune import lookup_cluster_knobs
                knobs = lookup_cluster_knobs(
                    n=n, entry_size=tbl.shape[1], hosts=hosts,
                    prf_method=prf_method,
                    cap=kw.get("cap", 512))
                if knobs:
                    buckets = knobs["buckets"]
                    kw.setdefault("max_in_flight", knobs["max_in_flight"])
            except Exception as e:  # tuning must never break serving
                note_swallowed("cluster.tune_lookup", e)
        kw.pop("cap", None)
        nodes = []
        plan = sorted(make_plan(n, hosts).items(),
                      key=lambda kv: int(kv[0][4:]))
        for i, (lb, row0s) in enumerate(plan):
            srv = ClusterShardServer(perm, row0s, g,
                                     prf_method=prf_method,
                                     budget_bytes=host_budget_bytes)
            nodes.append(LocalHost(lb, srv, process_index=i,
                                   buckets=buckets, injector=injector,
                                   **kw))
        router_kw.setdefault("spare_engine_kw",
                             dict(kw, buckets=buckets))
        return cls(nodes, granule=g, table_perm=perm, injector=injector,
                   **router_kw)

    # ---------------------------------------------------------- serving

    def submit(self, keys) -> ClusterFuture:
        """Scatter one batch to every covering host; returns a merged
        future.  Keys decode ONCE at the front-end (hosts receive the
        packed batch).  A host loss observed during the scatter runs
        recovery and raises ``HostUnreachable`` — ``submit_resilient``
        retries on the recovered plan."""
        pk = (keys if isinstance(keys, keygen.PackedKeys)
              else keygen.decode_keys_batched(keys))
        return ClusterFuture(self, pk, self._scatter(pk))

    def _scatter(self, pk) -> list:
        plan = self._scatter_plan()
        FLIGHT.record(
            "scatter", hosts=sorted(lb for lb, _ in plan),
            batch=pk.batch,
            arrival=getattr(self.injector, "arrival", None),
            granules={lb: len(node.granules) for lb, node in plan})
        parts = []
        for lb, node in plan:
            try:
                parts.append((lb, node.submit(pk)))
            except (LoadShed, DeadlineExceeded):
                raise                # decisions, not faults
            except (HostDropped, EngineDead, HostUnreachable) as e:
                self._handle_drop(lb, e)
                raise HostUnreachable(
                    "host %r lost mid-scatter (recovered; resubmit): %s"
                    % (lb, e)) from e
            except Exception as e:
                if self._note_failure(lb, e):
                    raise HostUnreachable(
                        "host %r breaker opened mid-scatter: %s"
                        % (lb, e)) from e
                raise
        return parts

    def submit_resilient(self, keys) -> ClusterFuture:
        """``submit`` + bounded retries across host-loss recoveries."""
        attempt = 0
        while True:
            try:
                return self.submit(keys)
            except (HostDropped, EngineDead, HostUnreachable):
                attempt += 1
                if attempt > self.max_retries:
                    raise
                self.recovery.inc("retries")

    def _scatter_plan(self) -> list:
        """(label, node) pairs covering the whole table: live hosts
        (breaker-open hosts are already down — the open callback ran
        recovery) plus the spare once ASSIGNED granules (an unpromoted
        hot standby holds only its warmup placeholder and stays out)."""
        with self._lock:
            plan = [(lb, node) for lb, node in self.hosts.items()
                    if lb not in self._down and node.granules]
            if self.spare is not None and self._assign.get("spare"):
                plan.append(("spare", self.spare))
            covered = set()
            for _, node in plan:
                covered.update(node.granules)
        missing = self._all_granules - covered
        if missing:
            raise ClusterUnavailable(
                "no live host covers granule rows %s"
                % sorted(missing)[:4])
        return plan

    def _merge(self, parts):
        """Wrapping int32 sum of per-host partial shares == the
        full-table additive share (disjoint row ranges commute with
        the share sum)."""
        out = np.array(parts[0], dtype=np.int32, copy=True)
        with np.errstate(over="ignore"):
            for p in parts[1:]:
                out += np.asarray(p, dtype=np.int32)
        return out

    # --------------------------------------------------------- liveness

    def check_hosts(self) -> dict:
        """Heartbeat sweep: probe every not-down host, running the
        recovery state machine for any that fail — host loss is
        detectable BETWEEN dispatches, not only when traffic hits the
        dead host.  Returns {label: state}."""
        for lb, node in list(self.hosts.items()):
            if lb in self._down:
                continue
            try:
                node.heartbeat()
            except (HostDropped, EngineDead, HostUnreachable) as e:
                self._handle_drop(lb, e)
            except Exception as e:
                self._note_failure(lb, e)
        return {lb: self.host_state(lb) for lb in self.hosts}

    def _note_ok(self, lb: str) -> None:
        br = self.breakers.get(lb)
        if br is not None and lb not in self._down:
            br.record_success()

    def _note_failure(self, lb: str, e) -> bool:
        """Count a transient failure on ``lb``'s breaker; True when the
        breaker is now open (the open callback already ran recovery)."""
        br = self.breakers.get(lb)
        if br is None:
            return False
        return br.record_failure() == "open"

    def _on_breaker_open(self, breaker) -> None:
        lb = breaker.name
        if lb in self.hosts and lb not in self._down:
            self._handle_drop(lb, HostUnreachable(
                "host %r breaker opened after %d consecutive failures"
                % (lb, breaker.consecutive)))

    # --------------------------------------------------------- recovery

    def _handle_drop(self, lb: str, err) -> None:
        """The recovery state machine: exclude the host, then answer
        the loss per ``policy`` (reshard over survivors, or degrade to
        the front-end spare).  Idempotent per host; serialized under
        the router lock so concurrent observers of one loss run ONE
        recovery."""
        with self._lock:
            if lb in self._down or lb not in self.hosts:
                return
            self._down.add(lb)
            arrival = getattr(self.injector, "arrival", None)
            FLIGHT.record("host_drop", host=lb, arrival=arrival,
                          error=type(err).__name__, detail=str(err))
            br = self.breakers.get(lb)
            while br is not None and br.state != "open":
                br.record_failure()   # loss confirmed: pin the breaker
            lost = self._assign.get(lb, ())
            self._assign[lb] = ()
            survivors = [l for l in self.hosts
                         if l not in self._down]
            decision = self.policy
            if decision == "auto":
                decision = "reshard" if survivors else "degrade"
            try:
                if decision == "reshard":
                    self._reshard(lost, survivors)
                else:
                    self._degrade(lost)
            except Exception as e:
                FLIGHT.record("cluster_recovery", host=lb,
                              decision=decision, ok=False,
                              error=type(e).__name__)
                raise ClusterUnavailable(
                    "recovery (%s) for host %r failed: %s"
                    % (decision, lb, e)) from e
            self.decision_counts[decision] += 1
            FLIGHT.record("cluster_recovery", host=lb, decision=decision,
                          granules=sorted(lost), arrival=arrival,
                          survivors=survivors, ok=True)

    def _reshard(self, lost, survivors) -> None:
        adds = reshard_plan(lost, survivors)
        for s_lb, row0s in adds.items():
            self.hosts[s_lb].add_granules(row0s)
            self._assign[s_lb] = tuple(
                sorted(set(self._assign[s_lb]) | set(row0s)))
        # a reshard re-homes table state, the cluster analogue of a
        # supervisor engine rebuild
        self.recovery.inc("engine_restarts")

    def _build_spare(self, row0s) -> LocalHost:
        if self._table_perm is None:
            raise ClusterUnavailable(
                "degrade needs the front-end table (table_perm=None)")
        if self._prf_method is None:
            raise ClusterUnavailable(
                "degrade needs prf_method (pass it to the router "
                "when hosts are remote)")
        srv = ClusterShardServer(self._table_perm, row0s, self.granule,
                                 prf_method=self._prf_method)
        kw = dict(self._spare_engine_kw)
        buckets = kw.pop("buckets", None)
        spare = LocalHost("spare", srv, buckets=buckets,
                          injector=self.injector, **kw)
        spare.warmup()
        return spare

    def _degrade(self, lost) -> None:
        if self.spare is None:
            self.spare = self._build_spare(lost)
        elif not self._assign.get("spare"):
            # promote the hot standby: swap its placeholder granule
            # for the dead host's real ones — device_put only, the
            # warmed programs already fit (row0 is traced)
            self.spare.server.set_granules(lost)
        else:
            self.spare.add_granules(lost)
        self._assign["spare"] = tuple(
            sorted(set(self._assign.get("spare", ())) | set(lost)))
        # dead granules fail over to the spare, batches keep flowing
        self.recovery.inc("failovers")

    # ---------------------------------------------------- observability

    def host_state(self, lb: str) -> str:
        """"live" | "degraded" (breaker not closed but not confirmed
        down) | "down"."""
        if lb == "spare":
            return "live" if (self.spare is not None
                              and self._assign.get("spare")) else "down"
        if lb in self._down:
            return "down"
        br = self.breakers.get(lb)
        if br is not None and br.state != "closed":
            return "degraded"
        return "live"

    @property
    def assignment(self) -> dict:
        with self._lock:
            return {lb: tuple(g) for lb, g in self._assign.items()}

    def counters(self) -> EngineCounters:
        """Cluster-merged serving counters: every host's engine ring +
        the spare's + the router-level recovery events
        (``EngineCounters.merge``)."""
        agg = EngineCounters()
        for lb, node in self.hosts.items():
            try:
                agg.merge(node.counters())
            except Exception as e:  # a dead host keeps no books; the
                # router-side recovery counters already recorded it
                note_swallowed("cluster.peer_unreachable", e,
                               self.recovery)
        if self.spare is not None:
            agg.merge(self.spare.counters())
        agg.merge(self.recovery)
        return agg

    def stats(self) -> dict:
        return {
            "hosts": {lb: self.host_state(lb) for lb in self.hosts},
            "assignment": {lb: list(g)
                           for lb, g in self.assignment.items()},
            "down": sorted(self._down),
            "decision_counts": dict(self.decision_counts),
            "counters": self.counters().as_dict(),
            "breakers": {lb: br.as_dict()
                         for lb, br in self.breakers.items()},
            "spare_granules": (list(self.spare.granules)
                               if self.spare is not None else []),
        }

    # ------------------------------------------------------- lifecycle

    def warmup(self) -> None:
        for lb, node in self.hosts.items():
            if lb not in self._down:
                node.warmup()

    def drain(self) -> None:
        for lb, node in self.hosts.items():
            if lb in self._down:
                continue
            try:
                node.drain()
            except Exception as e:  # a dying host must not block the
                # drain of the healthy ones
                note_swallowed("cluster.drain", e, self.recovery)
        if self.spare is not None:
            self.spare.drain()

    def close(self) -> None:
        for node in self.hosts.values():
            try:
                node.close()
            except Exception as e:
                note_swallowed("cluster.close", e, self.recovery)


# ------------------------------------------------- batch-PIR group routing

class ClusterPIRRouter:
    """Bin-sharded batch-PIR over cluster hosts with per-size-group
    routing (the PR-11 remainder).

    Full-domain DPF batches cannot skip granules — an additive share is
    pseudorandom over EVERY row, so every covering host must see every
    batch (``ClusterRouter``'s scatter).  Batch-PIR is different: each
    BIN is an independent padded mini-table with its own keys, so the
    whole bin is the natural routing unit.  Bins are laid out in
    descending padded-size order (a stable layout both sides can derive)
    and partitioned contiguously over hosts balanced by padded rows —
    each host's slice of that virtual row space is its granule, and,
    because equal-size bins are contiguous in the layout, each (n, G)
    size group lands on a contiguous few hosts rather than all of them.

    ``routed=True`` (the new path) dispatches each size group's keys
    ONLY to the hosts whose bins cover it; ``routed=False`` replays the
    pre-PR behaviour — every size group is delivered to every host and
    the host drops the foreign bins.  Both produce bit-identical
    per-bin answers (each bin has exactly one owner; the parity test
    gates routed vs broadcast vs the single-server oracle) — the
    difference is ``dispatch_counts``: per-host size-group deliveries,
    which the ``--multihost`` bench asserts shrink under routing.

    Hosts run ordinary :class:`~dpf_tpu.apps.batch_pir.
    PrivateLookupServer` instances over their owned bins, so the
    per-group construction resolution, packed wire-codec ingest and
    async all-groups dispatch are exactly the single-host production
    path.  ``scheme="auto"`` is rejected: its per-group construction
    choice consults the tuning cache keyed by GROUP size, which differs
    between a host's slice and the client's global view — the client
    and every host must derive identical constructions from the
    arguments alone.
    """

    def __init__(self, table, bins, hosts: int = 2, *, prf=None,
                 radix: int = 2, scheme: str = "logn",
                 routed: bool = True):
        from ..apps.batch_pir import PrivateLookupServer, _pad_pow2
        if scheme == "auto":
            raise ValueError(
                "ClusterPIRRouter needs a concrete scheme: 'auto' "
                "resolves per-group constructions from the tuning "
                "cache keyed by group size, which differs between a "
                "host's bin slice and the client's global view")
        if hosts < 1:
            raise ValueError("hosts must be >= 1 (got %d)" % hosts)
        self.routed = bool(routed)
        self.bins = [sorted(b) for b in bins]
        padded = [_pad_pow2(max(1, len(b))) for b in self.bins]
        # stable descending-size layout: equal-size bins contiguous
        order = sorted(range(len(self.bins)),
                       key=lambda i: (-padded[i], i))
        # contiguous partition balanced by padded rows
        total = sum(padded)
        target = total / hosts
        shards: list[list[int]] = [[] for _ in range(hosts)]
        h = acc = 0
        for bi in order:
            if (h < hosts - 1 and acc >= target * (h + 1)
                    and shards[h]):
                h += 1
            shards[h].append(bi)
            acc += padded[bi]
        self._hosts = []           # [(label, server, global bin idxs)]
        for i, idxs in enumerate(shards):
            lb = "pirhost%d" % i
            srv = (PrivateLookupServer(
                       np.asarray(table),
                       [self.bins[bi] for bi in idxs], prf=prf,
                       radix=radix, scheme=scheme)
                   if idxs else None)
            self._hosts.append((lb, srv, tuple(idxs)))
        self.group_sizes = tuple(sorted(set(padded), reverse=True))
        self._padded = padded
        #: {size: [labels owning >= 1 bin of that size group]}
        self.owners = {
            n: [lb for lb, _, idxs in self._hosts
                if any(padded[bi] == n for bi in idxs)]
            for n in self.group_sizes}
        self.dispatch_counts = {lb: 0 for lb, _, _ in self._hosts}
        self.entry_size = int(np.asarray(table).shape[1])

    def host_groups(self, label: str) -> tuple:
        """Padded sizes of the size groups ``label``'s bins cover."""
        for lb, _, idxs in self._hosts:
            if lb == label:
                return tuple(sorted({self._padded[bi] for bi in idxs},
                                    reverse=True))
        raise KeyError(label)

    def answer(self, keys_per_bin) -> np.ndarray:
        """Per-bin answer shares ``[n_bins, E]`` for one query round
        (same contract as ``PrivateLookupServer.answer``; the client
        side is unchanged).  Routed mode delivers each size group only
        to its owner hosts; broadcast mode delivers every group to
        every host (which drops foreign bins) — ``dispatch_counts``
        records the per-host deliveries either way."""
        if len(keys_per_bin) != len(self.bins):
            raise ValueError("expected one key per bin (%d), got %d"
                             % (len(self.bins), len(keys_per_bin)))
        out = np.zeros((len(self.bins), self.entry_size),
                       dtype=np.int32)
        total = 0
        for lb, srv, idxs in self._hosts:
            if self.routed:
                if not idxs:
                    continue  # no bins -> no group routed here
                delivered = len({self._padded[bi] for bi in idxs})
            else:
                delivered = len(self.group_sizes)
            self.dispatch_counts[lb] += delivered
            total += delivered
            if srv is None or not idxs:
                continue
            ans = np.asarray(srv.answer([keys_per_bin[bi]
                                         for bi in idxs]))
            out[list(idxs)] = ans
        FLIGHT.record(
            "pir_scatter", routed=self.routed, dispatches=total,
            hosts={lb: len(idxs) for lb, _, idxs in self._hosts},
            groups=len(self.group_sizes))
        return out

    def stats(self) -> dict:
        return {
            "routed": self.routed,
            "group_sizes": list(self.group_sizes),
            "owners": {int(n): list(lbs)
                       for n, lbs in self.owners.items()},
            "bins_per_host": {lb: len(idxs)
                              for lb, _, idxs in self._hosts},
            "dispatch_counts": dict(self.dispatch_counts),
        }
