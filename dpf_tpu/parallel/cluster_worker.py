"""Serving-cluster worker process (``python -m
dpf_tpu.parallel.cluster_worker <hex-pickled-config>``).

One worker = one serving host: it rebuilds the rehearsal table
deterministically from its config (``cluster_net.make_table`` — no
table bytes cross the wire), permutes it, wraps its granules in a
``ClusterShardServer`` + ``ServingEngine``, then answers framed-pickle
requests on a localhost TCP socket (port 0 = ephemeral; the chosen
port is published as a ``PORT <p>`` line on stdout for the parent).

Requests are handled strictly sequentially, so replies are FIFO — the
``RemoteHost`` client pipelines against that guarantee.  Config keys:

  label, row0s, granule, n, entry_size, table_seed, prf_method,
  process_index, port (0), buckets, max_in_flight,
  fault_plan (optional: {"seed", "specs": [FaultSpec kwargs]} so a
  worker can injected-kill ITSELF deterministically), and
  distributed (optional: {"coordinator_address", "num_processes",
  "process_id", "timeout_s"} to join a jax.distributed cluster when
  the jax build supports multiprocess CPU).

The worker stamps its flight/metrics output with ``process_index``
(``obs.set_process_index``) so merged cross-host observability stays
attributable, and ships ``obs.record_sections()`` in its ``stats``
reply.
"""

from __future__ import annotations

import pickle
import socket
import sys


def _build(config):
    """Build this host's shard server + engine from the config."""
    import numpy as np  # noqa: F401  (jax import below needs the env set)
    from ..core import expand
    from ..obs import set_process_index
    from ..parallel.cluster import ClusterShardServer, LocalHost
    from .cluster_net import make_table

    if config.get("process_index") is not None:
        set_process_index(int(config["process_index"]))
    dist = config.get("distributed")
    if dist:
        from . import multihost
        multihost.initialize(
            coordinator_address=dist.get("coordinator_address"),
            num_processes=dist.get("num_processes"),
            process_id=dist.get("process_id"),
            initialization_timeout_s=dist.get("timeout_s"))
    injector = None
    fp = config.get("fault_plan")
    if fp:
        from ..serve.faults import FaultPlan, FaultSpec
        injector = FaultPlan([FaultSpec(**s) for s in fp["specs"]],
                             seed=fp.get("seed", 0)).injector()
    table = make_table(config["n"], config["entry_size"],
                       config.get("table_seed", 0))
    perm = expand.permute_table(table)
    srv = ClusterShardServer(perm, config["row0s"], config["granule"],
                             prf_method=config["prf_method"])
    node = LocalHost(config["label"], srv,
                     process_index=config.get("process_index"),
                     buckets=config.get("buckets"), injector=injector,
                     max_in_flight=config.get("max_in_flight", 2))
    return node, injector


def _handle(node, injector, req):
    """One request -> one reply dict ({"ok": True, ...} or an error
    envelope carrying the exception class name for the client to
    re-raise as the right cluster error)."""
    from ..core import keygen
    from .cluster_net import pk_from_wire

    op = req.get("op")
    if op == "hello":
        return {"ok": True, "host": node.label,
                "granules": list(node.granules), "n": node.server.n,
                "entry_size": node.server.entry_size,
                "process_index": node.process_index}
    if op == "serve":
        if injector is not None:
            arrival = req.get("arrival")
            if arrival is not None:
                injector.begin_arrival(int(arrival))
        pk = pk_from_wire(req["pk"])
        if not isinstance(pk, keygen.PackedKeys):  # defensive
            raise TypeError("serve needs a packed batch")
        return {"ok": True, "out": node.submit(pk).result()}
    if op == "heartbeat":
        return {"ok": True, "status": node.heartbeat()}
    if op == "add_granules":
        node.add_granules(req["row0s"])
        return {"ok": True, "granules": list(node.granules)}
    if op == "counters":
        return {"ok": True, "counters": node.counters().as_dict()}
    if op == "stats":
        from ..obs import record_sections
        return {"ok": True,
                "stats": dict(node.stats(), obs=record_sections())}
    if op == "warmup":
        node.warmup()
        return {"ok": True}
    if op == "drain":
        node.drain()
        return {"ok": True}
    if op == "shutdown":
        return {"ok": True, "bye": True}
    return {"ok": False, "error": "ValueError",
            "detail": "unknown op %r" % (op,)}


def serve_forever(config) -> int:
    """Bind, publish the port, build the host, answer until shutdown
    or EOF.  Returns the exit code."""
    from .cluster_net import recv_frame, send_frame

    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", int(config.get("port", 0))))
    lsock.listen(1)
    # publish AFTER bind, BEFORE the (slow) jax-touching build: the
    # parent's connect then waits in the accept backlog while warmup
    # compiles, instead of timing out on a silent child
    print("PORT %d" % lsock.getsockname()[1], flush=True)
    node, injector = _build(config)
    conn, _ = lsock.accept()
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        while True:
            try:
                req = recv_frame(conn)
            except (ConnectionError, EOFError):
                return 0          # parent went away: clean exit
            try:
                reply = _handle(node, injector, req)
            except BaseException as e:  # noqa: BLE001 — the envelope IS
                # the error channel; the client re-raises by class name
                reply = {"ok": False, "error": type(e).__name__,
                         "detail": str(e)}
            send_frame(conn, reply)
            if reply.get("bye"):
                return 0
    finally:
        conn.close()
        lsock.close()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m dpf_tpu.parallel.cluster_worker "
              "<hex-pickled-config>", file=sys.stderr)
        return 2
    config = pickle.loads(bytes.fromhex(argv[0]))
    return serve_forever(config)


if __name__ == "__main__":
    sys.exit(main())
