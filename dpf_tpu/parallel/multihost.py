"""Multi-host scale-out: jax.distributed init + global-mesh construction.

The reference has no multi-node path at all (SURVEY.md §2.4); the TPU
equivalent of a NCCL/MPI backend is ``jax.distributed`` over DCN for
process coordination with XLA collectives over ICI inside each slice.  This
module wraps the standard recipe so a multi-host DPF server is:

    multihost.initialize()                       # once per process
    mesh = multihost.global_mesh(n_batch=2)      # ("batch", "table")
    srv = sharded.ShardedDPFServer(table, mesh)  # same code as single host

Laying the "table" axis innermost keeps the psum share-reduction on
ICI-adjacent devices; the "batch" axis (independent queries) tolerates DCN.
On a single host these helpers degrade to the local device set, so the same
program runs everywhere (tests exercise exactly that path).
"""

from __future__ import annotations

import numpy as np

_initialized = False  # explicit module state: initialize() succeeded here
_init_error: str | None = None  # why the last silent fallback happened


def is_initialized() -> bool:
    """True if this process's jax.distributed client is up (either via
    ``initialize`` here or an earlier ``jax.distributed.initialize``)."""
    if _initialized:
        return True
    try:  # reflect external initialization (e.g. a launcher did it)
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               initialization_timeout_s: float | None = None):
    """Initialize jax.distributed for multi-process runs (idempotent).

    With explicit arguments, failures propagate.  With no arguments,
    initialization is attempted unconditionally — on TPU pod slices JAX's
    cluster auto-detection supplies everything — and a detection failure
    (plain single-process run, tests) degrades to a no-op returning False
    with the cause recorded (``process_info().init_error`` /
    ``init_error()``) so a half-formed cluster is visible.

    ``initialization_timeout_s`` bounds the coordinator handshake: with
    explicit coordinator args and a coordinator that never comes up,
    jax's default is a 300 s hang — a worker in a crash-looping pod
    should fail fast instead.  The timeout cause (like every failure
    cause now) is surfaced through the ``init_error`` channel even on
    the raising paths, so post-mortems see WHY, not just a stack.
    """
    global _initialized, _init_error
    import jax
    if is_initialized():
        _init_error = None
        return True
    kw = {}
    if initialization_timeout_s is not None:
        t = max(1, int(initialization_timeout_s))
        kw = _timeout_kwargs(jax, t)
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kw)
        _initialized = True
        _init_error = None
        _label_observability(jax)
        return True
    except Exception as e:
        # belt-and-braces for external initialization on JAX versions
        # where the private-state probe in is_initialized() is stale
        if "already initialized" in str(e).lower():
            _initialized = True
            _init_error = None
            _label_observability(jax)
            return True
        cause = "%s: %s" % (type(e).__name__, e)
        if initialization_timeout_s is not None and _looks_like_timeout(e):
            cause = ("InitializationTimeout: coordinator %s did not "
                     "respond within %.0fs (%s)"
                     % (coordinator_address or "<auto>",
                        initialization_timeout_s, cause))
        # keep the cause on EVERY path — a raising worker's init_error()
        # is what the launcher/post-mortem reads
        _init_error = cause
        if (coordinator_address is not None or num_processes is not None
                or process_id is not None or _cluster_expected()):
            raise  # a real cluster failed to initialize: surface it
        # no cluster detected: single-process run — but keep the cause:
        # on a real pod a mis-set env var lands here and the only
        # symptom is process_count()==1
        return False


def _timeout_kwargs(jax_mod, timeout_s: int) -> dict:
    """``initialization_timeout`` pass-through when this jax supports it
    (>= 0.4.15); absent, the timeout degrades to jax's default with the
    degradation recorded (never a silent drop of the caller's bound)."""
    import inspect
    global _init_error
    try:
        params = inspect.signature(
            jax_mod.distributed.initialize).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        params = {}
    if "initialization_timeout" in params:
        return {"initialization_timeout": timeout_s}
    from ..utils.profiling import note_swallowed
    note_swallowed("multihost.timeout_unsupported", RuntimeError(
        "jax.distributed.initialize has no initialization_timeout "
        "parameter on jax %s" % getattr(jax_mod, "__version__", "?")))
    return {}


def _looks_like_timeout(e: BaseException) -> bool:
    msg = str(e).lower()
    return ("timeout" in msg or "timed out" in msg
            or "deadline" in msg or isinstance(e, TimeoutError))


def _label_observability(jax_mod) -> None:
    """Stamp this process's flight/metrics output with its rank."""
    try:
        from ..obs import set_process_index
        set_process_index(jax_mod.process_index())
    except Exception:  # observability must never break init
        pass


def _cluster_expected() -> bool:
    """Heuristic: does the environment look multi-process?  Used to decide
    whether an auto-detect initialization failure is a real error.

    ``DPF_EXPECT_CLUSTER`` is the explicit override in both directions
    ("1"/"true" forces loud failure, "0"/"false" forces the silent
    single-process fallback); otherwise coordinator-address vars, a
    multi-worker TPU hostname list, and the ``JAX_NUM_PROCESSES``-style
    launcher hints all mean a mis-launched pod should fail loudly
    instead of silently serving from one process."""
    import os
    explicit = os.environ.get("DPF_EXPECT_CLUSTER", "").strip().lower()
    if explicit:
        return explicit not in ("0", "false", "no", "off")
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or \
            os.environ.get("COORDINATOR_ADDRESS"):
        return True
    for var in ("JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "") or 0) > 1:
                return True
        except ValueError:
            pass  # an unparsable hint is not a cluster claim
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return "," in hosts  # more than one worker host


def global_mesh(n_batch: int = 1, n_table: int | None = None):
    """("batch", "table") mesh over ALL processes' devices.

    The "table" (psum) axis is laid out over the trailing device dimension
    — ICI-contiguous on TPU slices; "batch" spans hosts/DCN.
    """
    import jax
    from ..parallel import sharded
    devices = np.asarray(jax.devices())  # global across processes
    return sharded.make_mesh(n_table=n_table, n_batch=n_batch,
                             devices=devices)


class ProcessInfo(tuple):
    """(process_index, process_count) that also carries why a silent
    ``initialize()`` fallback happened: ``init_error`` is the recorded
    failure cause (None when init succeeded or was never attempted).
    A plain 2-tuple to existing callers — ``pi, pc = process_info()``
    keeps working."""
    init_error: str | None

    def __new__(cls, index, count, init_error=None):
        self = super().__new__(cls, (index, count))
        self.init_error = init_error
        return self

    @property
    def index(self):
        return self[0]

    @property
    def count(self):
        return self[1]


def init_error() -> str | None:
    """The recorded cause of the last silent ``initialize()`` fallback
    (None = initialized, or never attempted)."""
    return _init_error


def process_info() -> ProcessInfo:
    """(process_index, process_count) — for logging/sharded IO; carries
    ``init_error`` so a half-formed cluster (initialize fell back to
    single-process) is visible where the process count is read."""
    import jax
    return ProcessInfo(jax.process_index(), jax.process_count(),
                       _init_error)
