"""Multi-host scale-out: jax.distributed init + global-mesh construction.

The reference has no multi-node path at all (SURVEY.md §2.4); the TPU
equivalent of a NCCL/MPI backend is ``jax.distributed`` over DCN for
process coordination with XLA collectives over ICI inside each slice.  This
module wraps the standard recipe so a multi-host DPF server is:

    multihost.initialize()                       # once per process
    mesh = multihost.global_mesh(n_batch=2)      # ("batch", "table")
    srv = sharded.ShardedDPFServer(table, mesh)  # same code as single host

Laying the "table" axis innermost keeps the psum share-reduction on
ICI-adjacent devices; the "batch" axis (independent queries) tolerates DCN.
On a single host these helpers degrade to the local device set, so the same
program runs everywhere (tests exercise exactly that path).
"""

from __future__ import annotations

import numpy as np

_initialized = False  # explicit module state: initialize() succeeded here


def is_initialized() -> bool:
    """True if this process's jax.distributed client is up (either via
    ``initialize`` here or an earlier ``jax.distributed.initialize``)."""
    if _initialized:
        return True
    try:  # reflect external initialization (e.g. a launcher did it)
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None):
    """Initialize jax.distributed for multi-process runs (idempotent).

    With explicit arguments, failures propagate.  With no arguments,
    initialization is attempted unconditionally — on TPU pod slices JAX's
    cluster auto-detection supplies everything — and a detection failure
    (plain single-process run, tests) degrades to a no-op returning False.
    """
    global _initialized
    import jax
    if is_initialized():
        return True
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        _initialized = True
        return True
    except Exception as e:
        # belt-and-braces for external initialization on JAX versions
        # where the private-state probe in is_initialized() is stale
        if "already initialized" in str(e).lower():
            _initialized = True
            return True
        if (coordinator_address is not None or num_processes is not None
                or process_id is not None or _cluster_expected()):
            raise  # a real cluster failed to initialize: surface it
        return False  # no cluster detected: single-process run


def _cluster_expected() -> bool:
    """Heuristic: does the environment look multi-process?  Used to decide
    whether an auto-detect initialization failure is a real error."""
    import os
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or \
            os.environ.get("COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return "," in hosts  # more than one worker host


def global_mesh(n_batch: int = 1, n_table: int | None = None):
    """("batch", "table") mesh over ALL processes' devices.

    The "table" (psum) axis is laid out over the trailing device dimension
    — ICI-contiguous on TPU slices; "batch" spans hosts/DCN.
    """
    import jax
    from ..parallel import sharded
    devices = np.asarray(jax.devices())  # global across processes
    return sharded.make_mesh(n_table=n_table, n_batch=n_batch,
                             devices=devices)


def process_info():
    """(process_index, process_count) — for logging/sharded IO."""
    import jax
    return jax.process_index(), jax.process_count()
