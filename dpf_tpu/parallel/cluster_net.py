"""Socket transport for the serving cluster: RemoteHost + worker spawn.

``parallel/cluster.py``'s router is transport-agnostic: any object with
the five-method host protocol (``submit``/``heartbeat``/
``add_granules``/``counters``/``stats``) can sit in its scatter plan.
This module supplies the out-of-process implementation used by the
forced-multiprocess rehearsal (``benchmark.py --multihost
--multiprocess``) and the always-on transport tests:

* **Framing** — length-prefixed pickle over a localhost TCP socket
  (trusted child processes only; the worker is spawned by the parent,
  never exposed).  One request/one reply, strictly FIFO per
  connection, so a client can pipeline: ``submit`` sends the request
  and returns a future whose ``result()`` drains replies in order.
* **``RemoteHost``** — the socket client implementing the host
  protocol.  Any transport failure (worker killed, socket reset, a
  timeout) surfaces as ``cluster.HostUnreachable`` — the router treats
  it exactly like an injected ``host_drop`` — and best-effort teardown
  paths route their suppressed errors through
  ``note_swallowed("cluster.peer_unreachable", ...)`` so silent peer
  loss stays visible in the swallowed-error registry.
* **``spawn_workers``** — fork ``cluster_worker`` children (one per
  host) on ephemeral ports and connect RemoteHosts.  Workers rebuild
  the table deterministically from ``make_table(n, entry_size, seed)``
  — the same helper the front-end uses — so no table bytes cross the
  socket.

The wire carries packed key batches (front-end decodes once), int32
partial-share replies, and small control dicts; a real deployment
would swap this file for its RPC stack while keeping cluster.py
unchanged.
"""

from __future__ import annotations

import pickle
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np

from ..core import keygen
from ..utils.profiling import EngineCounters, note_swallowed
from .cluster import HostUnreachable

_LEN = struct.Struct(">I")
#: per-reply receive timeout (seconds) — a worker that stops answering
#: is a dead host, not a slow one
DEFAULT_TIMEOUT_S = 30.0


def make_table(n: int, entry_size: int, seed: int) -> np.ndarray:
    """The deterministic rehearsal table BOTH sides build (worker from
    its config, front-end for the oracle/spare) — no table bytes on the
    wire."""
    rng = np.random.default_rng(seed)
    return rng.integers(-2**31, 2**31, size=(n, entry_size),
                        dtype=np.int32)


def send_frame(sock, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock):
    head = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(head)
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock, count: int) -> bytes:
    buf = bytearray()
    while len(buf) < count:
        chunk = sock.recv(count - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def pk_to_wire(pk: keygen.PackedKeys) -> dict:
    return {"cw1": pk.cw1, "cw2": pk.cw2, "last": pk.last,
            "depth": pk.depth, "n": pk.n}


def pk_from_wire(d: dict) -> keygen.PackedKeys:
    return keygen.PackedKeys(cw1=d["cw1"], cw2=d["cw2"], last=d["last"],
                             depth=int(d["depth"]), n=int(d["n"]))


class _ReplySlot:
    __slots__ = ("value", "filled")

    def __init__(self):
        self.value = None
        self.filled = False


class RemoteFuture:
    """FIFO-pipelined result handle for one remote ``serve`` call."""

    def __init__(self, host, slot):
        self._host = host
        self._slot = slot

    def done(self) -> bool:
        return self._slot.filled

    def result(self):
        out = self._host._wait(self._slot)
        if not out.get("ok"):
            raise self._host._as_error(out)
        return out["out"]


class RemoteHost:
    """Host-protocol client over one worker socket.

    Mirrors ``cluster.LocalHost``; every transport failure raises
    ``HostUnreachable`` so the router's recovery state machine treats a
    killed worker exactly like an injected host drop."""

    def __init__(self, address, label: str, *,
                 process_index: int | None = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S, proc=None):
        self.label = label
        self.process_index = process_index
        self.proc = proc                  # the Popen, when we spawned it
        self._timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._slots = []                  # unread reply slots, FIFO
        self._sock = socket.create_connection(address,
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = self._call({"op": "hello"})
        self._granules = tuple(hello["granules"])
        self.n = int(hello["n"])
        self.entry_size = int(hello["entry_size"])
        if process_index is None:
            self.process_index = hello.get("process_index")

    # ----------------------------------------------------------- wire

    def _send(self, req) -> _ReplySlot:
        slot = _ReplySlot()
        with self._lock:
            try:
                send_frame(self._sock, req)
            except OSError as e:
                raise HostUnreachable(
                    "host %r unreachable on send: %s"
                    % (self.label, e)) from e
            self._slots.append(slot)
        return slot

    def _wait(self, slot: _ReplySlot):
        with self._lock:
            while not slot.filled:
                try:
                    reply = recv_frame(self._sock)
                except (OSError, EOFError, ConnectionError,
                        pickle.UnpicklingError) as e:
                    raise HostUnreachable(
                        "host %r unreachable on recv: %s"
                        % (self.label, e)) from e
                head = self._slots.pop(0)
                head.value = reply
                head.filled = True
        return slot.value

    def _call(self, req):
        out = self._wait(self._send(req))
        if not out.get("ok"):
            raise self._as_error(out)
        return out

    def _as_error(self, out) -> Exception:
        from ..serve.faults import HostDropped
        name = out.get("error", "RuntimeError")
        detail = out.get("detail", "")
        if name in ("HostDropped", "EngineDead"):
            return HostDropped("host %r: %s" % (self.label, detail))
        return RuntimeError("host %r %s: %s" % (self.label, name, detail))

    # -------------------------------------------------- host protocol

    def submit(self, pk) -> RemoteFuture:
        if not isinstance(pk, keygen.PackedKeys):
            pk = keygen.decode_keys_batched(pk)
        return RemoteFuture(self, self._send({"op": "serve",
                                              "pk": pk_to_wire(pk)}))

    def heartbeat(self) -> dict:
        # unwrap to the status dict so the node protocol matches
        # LocalHost.heartbeat exactly
        return self._call({"op": "heartbeat"})["status"]

    def add_granules(self, row0s) -> None:
        out = self._call({"op": "add_granules",
                          "row0s": [int(r) for r in row0s]})
        self._granules = tuple(out["granules"])

    @property
    def granules(self) -> tuple:
        return self._granules

    def counters(self) -> EngineCounters:
        """The worker's additive counter fields rebuilt into a local
        ``EngineCounters`` so ``ClusterRouter.counters()`` merges
        remote hosts like local ones (latency ring stays worker-side;
        the scalar SLO/fault fields all transfer)."""
        out = self._call({"op": "counters"})
        agg = EngineCounters()
        for name, value in out["counters"].items():
            if hasattr(agg, name) and isinstance(value, (int, float)) \
                    and not name.startswith("_"):
                try:
                    agg.inc(name, value)
                except Exception:
                    pass    # derived/readonly field — ring stays remote
        return agg

    def stats(self) -> dict:
        return self._call({"op": "stats"})["stats"]

    def warmup(self) -> None:
        self._call({"op": "warmup"})

    def drain(self) -> None:
        self._call({"op": "drain"})

    def kill(self) -> None:
        """Hard-kill the worker process (chaos legs): the next
        touch raises ``HostUnreachable`` — a REAL host death, detected
        through the same path as an injected one."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()

    def close(self) -> None:
        try:
            self._send({"op": "shutdown"})
        except Exception as e:
            # the peer may already be gone (chaos legs kill it); the
            # suppressed cause stays visible in the swallowed registry
            note_swallowed("cluster.peer_unreachable", e)
        try:
            self._sock.close()
        except OSError as e:
            note_swallowed("cluster.peer_unreachable", e)
        if self.proc is not None:
            try:
                self.proc.wait(timeout=5)
            except Exception as e:
                note_swallowed("cluster.peer_unreachable", e)
                self.proc.kill()


# -------------------------------------------------------------- spawn

def spawn_worker(config: dict, *, timeout_s: float = 60.0):
    """Start one ``cluster_worker`` child on an ephemeral port; returns
    a connected ``RemoteHost``.  ``config`` needs label/row0s/granule/
    n/entry_size/table_seed/prf_method (see cluster_worker.main)."""
    cfg = dict(config)
    cfg.setdefault("port", 0)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dpf_tpu.parallel.cluster_worker",
         pickle.dumps(cfg).hex()],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + timeout_s
    port = None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT "):
            port = int(line.split()[1])
            break
    if port is None:
        proc.kill()
        raise HostUnreachable(
            "worker %r never published its port (exit=%s)"
            % (cfg.get("label"), proc.poll()))
    return RemoteHost(("127.0.0.1", port), cfg["label"],
                      process_index=cfg.get("process_index"),
                      timeout_s=timeout_s, proc=proc)


def spawn_cluster(n: int, entry_size: int, hosts: int, *,
                  table_seed: int = 0, prf_method: int | None = None,
                  buckets=None, max_in_flight: int = 2,
                  timeout_s: float = 60.0):
    """Spawn one worker per host over the deterministic rehearsal table
    and return the connected ``RemoteHost`` list (plan order)."""
    from .cluster import make_plan
    if prf_method is None:
        from ..api import DPF
        prf_method = DPF.DEFAULT_PRF
    plan = sorted(make_plan(n, hosts).items(),
                  key=lambda kv: int(kv[0][4:]))
    nodes = []
    try:
        for i, (lb, row0s) in enumerate(plan):
            nodes.append(spawn_worker({
                "label": lb, "row0s": list(row0s),
                "granule": n // hosts, "n": n,
                "entry_size": entry_size, "table_seed": table_seed,
                "prf_method": prf_method, "process_index": i,
                "buckets": list(buckets) if buckets else None,
                "max_in_flight": max_in_flight,
            }, timeout_s=timeout_s))
    except Exception:
        for node in nodes:
            try:
                node.kill()
            except Exception as e:
                note_swallowed("cluster.peer_unreachable", e)
        raise
    return nodes
