"""Force a hermetic multi-device CPU JAX platform (virtual mesh).

Single source of truth for the recipe used by both ``tests/conftest.py``
and ``__graft_entry__.dryrun_multichip``: this environment's sitecustomize
registers the axon TPU PJRT plugin in every Python process and pins
``jax_platforms`` to ``"axon,cpu"`` at interpreter start, so env vars alone
cannot force CPU — and with the relay wedged, any first backend touch hangs
forever.  The fix is to rewrite ``XLA_FLAGS`` and update ``jax_platforms``
*before* the first backend initialization.
"""

import os
import re


def force_cpu_mesh(n_devices: int = 8, verify: bool = True) -> None:
    """Pin JAX to the CPU platform with ``n_devices`` virtual devices.

    Must be called before any JAX backend initialization (device query,
    compile, or array op).  Raises RuntimeError if a backend was already
    initialized in this process — the flags can no longer take effect and
    the caller needs a fresh process.

    ``verify=False`` skips the final ``jax.default_backend()`` check —
    that call itself initializes the backend, which must not happen yet
    when the caller still has to run ``jax.distributed.initialize``
    (multi-process tests); such callers verify after distributed init.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    flag = "--xla_force_host_platform_device_count=%d" % n_devices
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        flags = (flags + " " + flag).strip()
    elif int(m.group(1)) < n_devices:
        # raise a too-small pre-existing count; keep a larger user override
        flags = flags[:m.start()] + flag + flags[m.end():]
    os.environ["XLA_FLAGS"] = flags

    import jax

    initialized = False
    try:
        from jax._src import xla_bridge
        initialized = xla_bridge.backends_are_initialized()
    except (ImportError, AttributeError):  # private API moved; best effort
        pass
    if initialized:
        # Idempotent no-op when a prior call already produced what we need
        # (e.g. conftest forced 8 CPU devices and a test then calls
        # dryrun_multichip in-process).
        if (jax.default_backend() == "cpu"
                and len(jax.devices()) >= n_devices):
            return
        raise RuntimeError(
            "force_cpu_mesh needs a fresh process: a JAX backend (%r, %d "
            "devices) was initialized before the CPU platform could be "
            "forced to %d devices"
            % (jax.default_backend(), len(jax.devices()), n_devices))

    # Must run before the first backend touch; raises rather than falling
    # through to a backend query, which would itself initialize the
    # (possibly wedged) relay backend.
    jax.config.update("jax_platforms", "cpu")
    if verify and jax.default_backend() != "cpu":
        raise RuntimeError(
            "failed to force the CPU platform: default backend is %r"
            % jax.default_backend())
