"""Runtime evaluation config (replaces the reference's compile-time flag
tiers — SURVEY.md §5: ``DPF_STRATEGY``/``PRF_METHOD``/``Z``/``BATCH_SIZE``
``-D`` flags become one dataclass; jit specializes per value).

Fields left at their *auto* state (``None`` or ``"auto"``) are resolved at
dispatch time: explicit values win, then per-shape knobs from the
persistent tuning cache (``tune/cache.py``, populated by
``benchmark.py --autotune``), then the static heuristics
(``expand.choose_chunk`` et al.).  ``is_auto`` defines the auto state;
``api.DPF.resolved_eval_knobs`` implements the precedence.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, replace


def is_auto(value) -> bool:
    """True when a knob is at its auto state (resolve via tuning cache
    then heuristic): ``None`` or the string ``"auto"``."""
    return value is None or value == "auto"


def check_construction(scheme: str, radix: int,
                       schemes=("logn", "sqrtn", "auto")) -> None:
    """The one scheme/radix membership rule for every construction
    surface — the ``DPF`` ctor and the batch-PIR server, client, and
    cost model all validate here.  Pass a narrower ``schemes`` tuple to
    drop "auto" at call sites that need a concrete construction."""
    if scheme not in schemes:
        raise ValueError("scheme must be one of %s (got %r)"
                         % (schemes, scheme))
    if radix not in (2, 4):
        raise ValueError("radix must be 2 or 4")
    if scheme == "sqrtn" and radix == 4:
        raise ValueError("scheme='sqrtn' has no radix; use radix=2")


@dataclass(frozen=True)
class EvalConfig:
    """Everything that selects a compiled evaluation program."""
    prf_method: int = 3  # PRF_AES128; 0..3 = reference ids, 4/5 =
    #                 SALSA20_BLK/CHACHA20_BLK block-PRG variants (one
    #                 512-bit core block feeds four GGM children —
    #                 core/prf_ref.py::prf_salsa20_12_blk)
    batch_size: int = 512          # device dispatch cap (reference parity)
    chunk_leaves: int | None = None  # None = auto (tuned, else choose_chunk)
    dot_impl: str | None = "i32"   # "i32" | "mxu" (ops/matmul128) |
    #                 None/"auto" (tuned, else module default)
    round_unroll: bool | None = None  # None = auto (unroll on TPU)
    aes_impl: str = "auto"  # "auto"|"gather"|"bitsliced"[":bp"|":tower"]
    kernel_impl: str | None = "xla"  # "xla" | "pallas" (ChaCha/Salsa subtree
    #                 kernel) | "dispatch" (per-level programs; fast compile)
    #                 | None/"auto" (tuned, else "xla")
    dispatch_group: int | None = None  # dispatch mode: frontier subtrees
    #                 expanded per pass (None = auto; larger = fewer host
    #                 round-trips, more live leaf memory per pass)
    radix: int = 2  # 2 = reference-wire-compatible binary GGM;
    #                 4 = TPU-native radix-4 (core/radix4.py): 2/3 the PRF
    #                 children, half the levels, 2x AES schedule amortization
    scheme: str = "logn"  # "logn" (GGM tree, O(log N) keys) | "sqrtn"
    #                 (core/sqrtn.py: O(sqrt N) keys, flat single-level PRF
    #                 grid — the latency play for mid-sized tables)
    row_chunk: int | None = None  # sqrtn: grid rows PRF-expanded per scan
    #                 step (None = auto: tuned, else sqrtn.choose_row_chunk
    #                 bounding the live [B, rc, K, 4] slab at the 64 MiB
    #                 CHUNK_SEED_BYTES_BOUND); multiple of 4, divides R

    def with_(self, **kw) -> "EvalConfig":
        return replace(self, **kw)

    def apply_globals(self):
        """Push the process-wide knobs (round_unroll, aes/dot defaults).

        Fields at their auto state RESET their global to its auto
        default (``ROUND_UNROLL=None``, ``AES_PAIR_IMPL="auto"``, dot
        ``"i32"``) — sweep scripts apply configs in sequence and must
        not leak one config's knobs into the next measurement.  Prefer
        the scoped ``applied()`` in any code that measures candidates."""
        from ..core import prf
        from ..ops import matmul128
        prf.ROUND_UNROLL = self.round_unroll
        prf.AES_PAIR_IMPL = (self.aes_impl
                             if not is_auto(self.aes_impl) else "auto")
        matmul128.set_dot_impl(self.dot_impl
                               if not is_auto(self.dot_impl) else "i32")
        return self

    @contextlib.contextmanager
    def applied(self):
        """Scoped ``apply_globals``: snapshot the process-wide knobs,
        push this config's values, and restore the snapshot on exit —
        exception or not.  The tuner wraps every candidate measurement
        in this so a crashed search can't leave the process mis-knobbed.
        """
        from ..core import prf
        from ..ops import matmul128
        snap = (prf.ROUND_UNROLL, prf.AES_PAIR_IMPL,
                matmul128.default_impl())
        try:
            yield self.apply_globals()
        finally:
            prf.ROUND_UNROLL, prf.AES_PAIR_IMPL = snap[0], snap[1]
            matmul128.set_dot_impl(snap[2])
