"""Runtime evaluation config (replaces the reference's compile-time flag
tiers — SURVEY.md §5: ``DPF_STRATEGY``/``PRF_METHOD``/``Z``/``BATCH_SIZE``
``-D`` flags become one dataclass; jit specializes per value).
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class EvalConfig:
    """Everything that selects a compiled evaluation program."""
    prf_method: int = 3  # PRF_AES128; 0..3 = reference ids, 4/5 =
    #                 SALSA20_BLK/CHACHA20_BLK block-PRG variants (one
    #                 512-bit core block feeds four GGM children —
    #                 core/prf_ref.py::prf_salsa20_12_blk)
    batch_size: int = 512          # device dispatch cap (reference parity)
    chunk_leaves: int | None = None  # None = auto (choose_chunk)
    dot_impl: str = "i32"          # "i32" | "mxu" (ops/matmul128)
    round_unroll: bool | None = None  # None = auto (unroll on TPU)
    aes_impl: str = "auto"  # "auto"|"gather"|"bitsliced"[":bp"|":tower"]
    kernel_impl: str = "xla"  # "xla" | "pallas" (ChaCha/Salsa subtree
    #                  kernel) | "dispatch" (per-level programs; fast compile)
    dispatch_group: int | None = None  # dispatch mode: frontier subtrees
    #                 expanded per pass (None = auto; larger = fewer host
    #                 round-trips, more live leaf memory per pass)
    radix: int = 2  # 2 = reference-wire-compatible binary GGM;
    #                 4 = TPU-native radix-4 (core/radix4.py): 2/3 the PRF
    #                 children, half the levels, 2x AES schedule amortization
    scheme: str = "logn"  # "logn" (GGM tree, O(log N) keys) | "sqrtn"
    #                 (core/sqrtn.py: O(sqrt N) keys, flat single-level PRF
    #                 grid — the latency play for mid-sized tables)

    def with_(self, **kw) -> "EvalConfig":
        return replace(self, **kw)

    def apply_globals(self):
        """Push the process-wide knobs (round_unroll, aes/dot defaults)."""
        from ..core import prf
        from ..ops import matmul128
        prf.ROUND_UNROLL = self.round_unroll
        prf.AES_PAIR_IMPL = self.aes_impl
        matmul128.set_dot_impl(self.dot_impl)
        return self
