"""Capability probes for jax/jaxlib features the repo degrades around.

The pinned container toolchain (jax/jaxlib 0.4.37) predates several
features the test suite and the mesh path lean on; each probe here
answers "can THIS process do X" so callers (tests, mostly) can skip
cleanly instead of failing on a known toolchain gap.  Everything is a
cheap attribute/version check — no backend initialization, so the
probes are safe to call before ``hermetic.force_cpu_mesh``.
"""

from __future__ import annotations


def jax_version() -> tuple:
    """jax's version as an int tuple (best effort: non-int parts drop)."""
    import jax
    out = []
    for part in jax.__version__.split("."):
        digits = "".join(c for c in part if c.isdigit())
        if not digits:
            break
        out.append(int(digits))
    return tuple(out)


def has_tpu_interpret_mode() -> bool:
    """True when Pallas ships the TPU-semantics interpreter
    (``pltpu.force_tpu_interpret_mode``, jax >= 0.4.38).  Without it the
    interpret-mode kernel tests cannot run on this host: the generic
    ``interpret=True`` engine compiles interpreted grids with XLA-CPU
    and blows up super-linearly (tests/test_pallas_level.py docstring).
    """
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover - pallas not shipped at all
        return False
    return hasattr(pltpu, "force_tpu_interpret_mode")


def has_pallas_sqrt_kernel(backend: str | None = None) -> bool:
    """True when the fused sqrt-N grid kernel (``ops/pallas_sqrt.py``)
    can compile AND run in this process: Pallas importable and the
    backend is TPU.  Elsewhere resolvers degrade to ``kernel_impl=
    "xla"`` with provenance (``api.resolved_eval_knobs`` reports
    ``kernel_resolved_from="degraded"`` and counts it via
    ``note_swallowed``) — the generic ``interpret=True`` engine is a
    debugging device, not a serving path (``has_tpu_interpret_mode``).
    Pass ``backend`` to probe without initializing one."""
    try:
        from jax.experimental import pallas  # noqa: F401
    except Exception:  # pragma: no cover - pallas not shipped at all
        return False
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:  # pragma: no cover - no usable backend
            return False
    return backend == "tpu"


def has_effects_barrier() -> bool:
    """True when ``jax.effects_barrier()`` exists (jax >= 0.4.x late
    line).  ``utils.profiling.Timer`` uses it to drain ALL in-flight
    async dispatches at exit; the legacy fallback — blocking on a fresh
    ``jnp.zeros(())`` — only proves one new dispatch completed, which
    on TPU leaves prior independent work un-drained."""
    import jax
    return callable(getattr(jax, "effects_barrier", None))


def device_memory_stats(device=None) -> dict | None:
    """``Device.memory_stats()`` as a plain dict, or None.

    On TPU (and CUDA) jaxlib exposes per-device allocator stats —
    notably ``bytes_limit`` (the HBM budget XLA will allocate against)
    and ``bytes_in_use``.  On CPU backends and older jaxlib the method
    is missing, returns None, or raises UNIMPLEMENTED; all of those
    collapse to a graceful ``None`` here so callers can treat "no
    stats" as "no device memory ceiling to plan around".

    ``plan/capacity.detect_hbm_budget`` seeds per-host HBM budgets from
    this probe when available.  NOTE: unlike the other probes in this
    module, resolving the default device initializes a backend — pass
    an explicit ``device`` (or call only after ``force_cpu_mesh``) in
    backend-order-sensitive code."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = getattr(device, "memory_stats", None)
        if stats is None:
            return None
        out = stats()
    except Exception:  # pragma: no cover - backend-specific failures
        return None
    return dict(out) if out else None


def has_cpu_multiprocess() -> bool:
    """True when the CPU backend supports multi-process computations
    (cross-process collectives).  jaxlib 0.4.x's CPU client raises
    ``INVALID_ARGUMENT: Multiprocess computations aren't implemented on
    the CPU backend`` from the first sharded ``device_put``; the
    capability landed in the 0.5 line."""
    return jax_version() >= (0, 5)
