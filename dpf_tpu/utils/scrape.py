"""Scrape printed-dict benchmark lines from logs into rows / CSV.

Counterpart of the reference's ``paper/kernel/gpu/scripts/scrape.py``:
benchmark binaries/scripts print one python-dict (or JSON) result line per
run; this collects the *last* such line of each log into a table.
"""

from __future__ import annotations

import ast
import csv
import glob
import json
import os


def parse_result_line(line: str):
    """A result line is a dict literal (JSON or python repr) -> dict|None."""
    line = line.strip()
    if not (line.startswith("{") and line.endswith("}")):
        return None
    for parser in (json.loads, ast.literal_eval):
        try:
            d = parser(line)
            return d if isinstance(d, dict) else None
        except (ValueError, SyntaxError):
            continue
    return None


def scrape_file(path: str):
    """Last result-dict line of a log file, or None."""
    result = None
    with open(path) as f:
        for line in f:
            d = parse_result_line(line)
            if d is not None:
                result = d
    return result


def scrape_dir(pattern: str):
    """Glob logs -> list of (filename, result dict)."""
    rows = []
    for path in sorted(glob.glob(pattern)):
        d = scrape_file(path)
        if d is not None:
            rows.append((os.path.basename(path), d))
    return rows


def to_csv(rows, out_path: str):
    """Write scraped (name, dict) rows to CSV with the union of keys."""
    keys = []
    for _, d in rows:
        for k in d:
            if k not in keys:
                keys.append(k)
    with open(out_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["log"] + keys)
        for name, d in rows:
            w.writerow([name] + [d.get(k, "") for k in keys])
    return out_path
