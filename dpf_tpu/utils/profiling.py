"""Profiling helpers (the reference's Nsight-Compute role, SURVEY.md §5).

``jax.profiler`` traces viewable in XProf/Perfetto replace ``ncu``; the
trace directory naming mirrors the reference's artifact-per-config scheme
(``paper/kernel/gpu/Makefile:24-26``).
"""

from __future__ import annotations

import contextlib
import os
import time


@contextlib.contextmanager
def trace(config_name: str, base_dir: str = "/tmp/dpf_tpu_traces"):
    """Capture a jax.profiler trace named after the benchmark config."""
    import jax
    path = os.path.join(base_dir, config_name)
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock block timer that blocks on device completion."""

    def __init__(self):
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import jax
        # drain any async dispatch before stopping the clock
        jax.block_until_ready(jax.numpy.zeros(()))
        self.elapsed = time.perf_counter() - self._t0
        return False
