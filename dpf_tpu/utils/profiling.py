"""Profiling helpers (the reference's Nsight-Compute role, SURVEY.md §5).

``jax.profiler`` traces viewable in XProf/Perfetto replace ``ncu``; the
trace directory naming mirrors the reference's artifact-per-config scheme
(``paper/kernel/gpu/Makefile:24-26``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
import warnings


@contextlib.contextmanager
def trace(config_name: str, base_dir: str = "/tmp/dpf_tpu_traces"):
    """Capture a jax.profiler trace named after the benchmark config."""
    import jax
    path = os.path.join(base_dir, config_name)
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()


def _self_times(track_events):
    """(name, self_us) per complete event of ONE track, with nested
    children's durations subtracted from their parents (host python
    stacks and runtime tracks nest; summing raw durations would count
    a frame once per ancestor)."""
    evs = sorted(track_events,
                 key=lambda e: (float(e.get("ts", 0)),
                                -float(e.get("dur", 0))))
    out = []
    stack = []  # indices into out; parents below children
    for e in evs:
        ts = float(e.get("ts", 0))
        dur = float(e.get("dur", 0))
        while stack and stack[-1][0] <= ts:
            stack.pop()
        if stack:
            parent = stack[-1][1]
            out[parent][1] -= dur
        out.append([str(e.get("name", "?"))[:80], dur])
        stack.append((ts + dur, len(out) - 1))
    return out


def summarize_trace(trace_dir: str, top: int = 12):
    """Digest a captured trace into {device_ms, top_ops} (or None).

    Reads the Chrome-trace export (``*.trace.json.gz``) the profiler
    writes next to the xplane protobuf, picks the op-level tracks —
    "XLA Ops" threads (TPU device traces), else ``tf_XLA*`` runtime
    threads (CPU backend), else everything — and aggregates SELF time
    per op name (module/parent rows span their children and would
    otherwise double-count).  The digest is small enough to live as a
    row in the measurement JSONL, so the TPU session's profile stage
    records WHERE the time went (the ncu-report role,
    ``paper/kernel/gpu/Makefile:24-32``) even if the raw trace
    directory is lost.
    """
    import glob
    import gzip
    import json as _json

    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        return None
    with gzip.open(paths[-1], "rt") as f:
        events = _json.load(f).get("traceEvents", [])
    thread_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = str(
                e.get("args", {}).get("name", ""))
    tracks = {}
    for e in events:
        if e.get("ph") == "X":
            tracks.setdefault((e.get("pid"), e.get("tid")), []).append(e)

    def pick(pred):
        return {k: v for k, v in tracks.items()
                if pred(thread_names.get(k, ""))}
    chosen = pick(lambda n: "XLA Ops" in n)          # TPU device tracks
    track_kind = "xla_ops"
    if not chosen:
        chosen = pick(lambda n: n.startswith("tf_XLA"))  # CPU runtime
        track_kind = "tf_xla"
    if not chosen:
        # unknown thread-naming scheme: totals include HOST tracks —
        # tagged so the digest is never mistaken for pure device time
        chosen = tracks
        track_kind = "all_tracks_incl_host"
    by_op = {}
    total_us = 0.0
    for track in chosen.values():
        for name, self_us in _self_times(track):
            total_us += self_us
            by_op[name] = by_op.get(name, 0.0) + self_us
    ops = sorted(by_op.items(), key=lambda kv: -kv[1])[:top]
    return {"trace_file": os.path.basename(paths[-1]),
            "tracks": track_kind,
            "device_ms": round(total_us / 1e3, 3),
            "top_ops": [{"op": k, "ms": round(v / 1e3, 3)}
                        for k, v in ops]}


def quantile(samples, q: float, *, presorted: bool = False) -> float:
    """Nearest-rank quantile of a sequence of floats (q in [0, 1]).

    Deliberately numpy-free: the latency ring is consulted on the
    admission-control hot path (every ``submit``), where an np.quantile
    round-trip would cost more than the dispatch it guards.
    ``presorted=True`` skips the sort (the ring keeps a cached sorted
    view for exactly that path)."""
    if not samples:
        raise ValueError("quantile of an empty sample set")
    if not 0.0 <= q <= 1.0:
        raise ValueError("q must be in [0, 1] (got %r)" % (q,))
    s = samples if presorted else sorted(samples)
    return s[min(len(s) - 1, max(0, int(q * len(s) + 0.5) - 1))]


#: bounded size of the per-engine latency ring: big enough that p99 over
#: it is stable, small enough that a long-lived engine's memory and the
#: per-submit quantile stay O(1)-ish
LATENCY_RING = 2048

#: fixed upper bounds (seconds) of the per-engine latency HISTOGRAM —
#: the mergeable cumulative complement of the ring's exact bounded-
#: window quantiles (the ring forgets, the histogram accumulates; the
#: OpenMetrics exporter in obs/metrics.py renders both).  1 ms .. 10 s
#: log-ish ladder, +Inf bucket implicit.
LATENCY_HIST_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                          0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _hist_zero() -> list:
    return [0] * (len(LATENCY_HIST_BUCKETS_S) + 1)


@dataclasses.dataclass
class EngineCounters:
    """Per-engine serving counters (serve/engine.py).

    Host pack time (vectorized decode + bucket pad), dispatch time (the
    jitted call — async enqueue on TPU, the compute itself on the
    synchronous CPU backend) and wait time (host blocking on device
    results) are split so the host/device overlap the engine buys is
    visible in the benchmark record.

    SLO accounting (docs/SERVING.md "Load testing & SLOs"): per-batch
    submit→result latencies land in a bounded ring (``note_latency``;
    p50/p95/p99 via ``quantile``) AND a fixed-bucket cumulative
    histogram (``latency_histogram``, rendered by the OpenMetrics
    exporter), ``deadline_misses`` counts cooperative-deadline trips,
    and ``shed_*`` count batches/queries the admission control rejected
    instead of queueing.  ``reset()`` and ``merge()`` let a router
    (serve/router.py) or ``LookupStream`` aggregate per-engine counters
    into one record without hand-copying fields.

    Mutation is THREAD-SAFE where threads actually race: the
    ``note_*`` recorders, ``inc()`` (the spelling for cross-thread
    ``field += n`` — supervisor rebuild threads and
    ``RoutedFuture.result()`` callers share a router's ``recovery``
    counters), ``merge``/``reset`` and the readers all hold the
    per-instance lock.  Single-owner hot-path writes inside
    ``ServingEngine`` (an engine is not itself a concurrent object)
    stay plain attribute updates.
    """
    batches_submitted: int = 0
    queries_submitted: int = 0
    dispatches: int = 0
    padded_queries: int = 0       # pad rows dispatched (bucket waste)
    in_flight_hwm: int = 0        # high-water mark of the dispatch window
    pack_time_s: float = 0.0
    dispatch_time_s: float = 0.0
    wait_time_s: float = 0.0
    deadline_misses: int = 0      # cooperative-deadline trips
    shed_batches: int = 0         # batches rejected by admission control
    shed_queries: int = 0         # queries inside those batches
    # fault-tolerance accounting (serve/faults.py, docs/SERVING.md
    # "Fault tolerance & chaos testing"): additive like the counters
    # above, so they flow through merge()/as_dict unchanged
    retries: int = 0              # re-attempts after a failed submit
    failovers: int = 0            # batches moved to another construction
    breaker_opens: int = 0        # circuit-breaker closed->open trips
    engine_restarts: int = 0      # supervisor engine rebuilds
    swallowed_errors: int = 0     # caught-and-suppressed exceptions
    #: bounded ring of recent per-batch latencies (seconds); leading
    #: underscore keeps the raw samples out of as_dict — records carry
    #: the quantiles, not 2048 floats
    _latencies: list = dataclasses.field(default_factory=list, repr=False)
    _lat_pos: int = 0
    #: sorted view of the ring, rebuilt lazily: admission control reads
    #: p99 on every submit, so the sort must not repeat while no new
    #: sample landed
    _lat_sorted: list | None = dataclasses.field(default=None,
                                                 repr=False)
    #: cumulative fixed-bucket histogram of every latency ever noted
    #: (the ring's mergeable complement; last slot is the +Inf bucket)
    _lat_hist: list = dataclasses.field(default_factory=_hist_zero,
                                        repr=False)
    _lat_hist_sum: float = dataclasses.field(default=0.0, repr=False)
    _lat_hist_count: int = dataclasses.field(default=0, repr=False)
    #: per-instance lock (RLock: as_dict -> quantile nests); excluded
    #: from ==/repr and NEVER replaced by reset() — a racing thread may
    #: hold it
    _lock: object = dataclasses.field(
        default_factory=threading.RLock, repr=False, compare=False)

    def inc(self, name: str, delta=1):
        """Thread-safe ``self.<name> += delta`` — the one spelling for
        counter bumps that can race across threads (supervisor rebuild
        threads, ``RoutedFuture.result()`` callers)."""
        with self._lock:
            setattr(self, name, getattr(self, name) + delta)

    def note_dispatch(self, padded: int, in_flight: int):
        with self._lock:
            self.dispatches += 1
            self.padded_queries += padded
            self.in_flight_hwm = max(self.in_flight_hwm, in_flight)

    def note_latency(self, seconds: float):
        """Record one batch's submit→result latency in the ring
        (overwriting the oldest sample once ``LATENCY_RING`` is full)
        and the cumulative fixed-bucket histogram."""
        s = float(seconds)
        with self._lock:
            if len(self._latencies) < LATENCY_RING:
                self._latencies.append(s)
            else:
                self._latencies[self._lat_pos] = s
                self._lat_pos = (self._lat_pos + 1) % LATENCY_RING
            self._lat_sorted = None
            i = 0
            while (i < len(LATENCY_HIST_BUCKETS_S)
                   and s > LATENCY_HIST_BUCKETS_S[i]):
                i += 1
            self._lat_hist[i] += 1
            self._lat_hist_sum += s
            self._lat_hist_count += 1

    def quantile(self, q: float) -> float | None:
        """Latency quantile over the ring (seconds), None when empty."""
        with self._lock:
            if not self._latencies:
                return None
            if self._lat_sorted is None:
                self._lat_sorted = sorted(self._latencies)
            return quantile(self._lat_sorted, q, presorted=True)

    def latency_histogram(self) -> dict:
        """The cumulative fixed-bucket latency histogram:
        ``{"buckets": bounds, "counts": per-bucket (+Inf last),
        "sum", "count"}`` — what the OpenMetrics exporter renders as
        ``dpf_engine_latency_seconds``."""
        with self._lock:
            return {"buckets": list(LATENCY_HIST_BUCKETS_S),
                    "counts": list(self._lat_hist),
                    "sum": round(self._lat_hist_sum, 6),
                    "count": self._lat_hist_count}

    @property
    def p50(self):
        return self.quantile(0.50)

    @property
    def p95(self):
        return self.quantile(0.95)

    @property
    def p99(self):
        return self.quantile(0.99)

    @property
    def pad_waste(self) -> float:
        """Fraction of dispatched query slots that were padding."""
        total = self.queries_submitted + self.padded_queries
        return self.padded_queries / total if total else 0.0

    def reset(self) -> "EngineCounters":
        """Zero every counter and drop the latency ring/histogram, in
        place (the lock itself survives — a racing thread may hold it)."""
        with self._lock:
            for f in dataclasses.fields(self):
                if f.name == "_lock":
                    continue
                setattr(
                    self, f.name,
                    f.default if f.default_factory is dataclasses.MISSING
                    else f.default_factory())
        return self

    def merge(self, other: "EngineCounters") -> "EngineCounters":
        """Fold ``other`` into self: sums for the additive counters, max
        for the high-water mark, both latency rings pooled and the
        histograms added bucket-wise.  A pool over the ring bound is
        DOWNSAMPLED by a uniform stride (not truncated) so every merged
        engine keeps proportional representation in the aggregate
        quantiles — a tail slice would silently reduce the aggregate to
        the last engine merged.  Returns self, so
        ``reduce(EngineCounters.merge, stats_list, EngineCounters())``
        builds one aggregate record.  Locks both instances in id order
        (no deadlock against a concurrent opposite-direction merge).
        Merging an instance into itself is a no-op (it would silently
        double every counter and duplicate the pooled latency ring)."""
        if other is self:
            return self
        first, second = ((self, other) if id(self) <= id(other)
                         else (other, self))
        with first._lock, second._lock:
            for f in dataclasses.fields(self):
                if f.name.startswith("_") or f.name == "in_flight_hwm":
                    continue
                setattr(self, f.name,
                        getattr(self, f.name) + getattr(other, f.name))
            self.in_flight_hwm = max(self.in_flight_hwm,
                                     other.in_flight_hwm)
            pooled = self._latencies + other._latencies
            if len(pooled) > LATENCY_RING:
                step = len(pooled) / LATENCY_RING
                pooled = [pooled[int(i * step)]
                          for i in range(LATENCY_RING)]
            self._latencies = pooled
            self._lat_pos = 0
            self._lat_sorted = None
            self._lat_hist = [a + b for a, b in
                              zip(self._lat_hist, other._lat_hist)]
            self._lat_hist_sum += other._lat_hist_sum
            self._lat_hist_count += other._lat_hist_count
        return self

    def as_dict(self) -> dict:
        with self._lock:
            d = {}
            for f in dataclasses.fields(self):
                if f.name.startswith("_"):
                    continue  # raw latency samples: summarized below
                v = getattr(self, f.name)
                d[f.name] = round(v, 6) if isinstance(v, float) else v
            d["pad_waste"] = round(self.pad_waste, 4)
            if self._latencies:
                d["latency_ms"] = {
                    "count": len(self._latencies),
                    "p50": round(self.p50 * 1e3, 3),
                    "p95": round(self.p95 * 1e3, 3),
                    "p99": round(self.p99 * 1e3, 3),
                }
            return d


@dataclasses.dataclass
class CacheCounters:
    """Process-wide cache-effectiveness counters (tune/ subsystem).

    ``tuning_*`` move on every persistent-tuning-cache lookup
    (``tune/cache.py``); ``compile_*`` mirror JAX's
    ``/jax/compilation_cache/*`` monitoring events once
    ``tune.compcache.enable()`` has registered its listener.  A warm
    second process shows ``tuning_hits > 0`` (autotune search skipped)
    and ``compile_hits > 0`` (XLA recompile skipped) — the assertion
    the warm-start test makes.
    """
    tuning_hits: int = 0
    tuning_misses: int = 0
    tuning_stores: int = 0
    compile_hits: int = 0
    compile_misses: int = 0
    compile_time_saved_s: float = 0.0

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["compile_time_saved_s"] = round(d["compile_time_saved_s"], 4)
        return d

    def reset(self) -> "CacheCounters":
        """Zero every counter in place (mirrors ``EngineCounters.reset``
        so tests and benches can scope cache measurements to one run)."""
        for f in dataclasses.fields(self):
            setattr(self, f.name, f.default)
        return self


CACHE_COUNTERS = CacheCounters()


#: process-wide registry of caught-and-suppressed exceptions:
#: site -> {exception class name -> count}.  The serving stack has
#: several deliberate "must never break serving" suppression points
#: (cache lookups, compile-cache enable, diagnostics); before this
#: registry they discarded the cause entirely, so a misconfigured cache
#: was indistinguishable from a cold one.  ``note_swallowed`` is the
#: one spelling of "suppress but stay diagnosable".
SWALLOWED_ERRORS: dict = {}
_SWALLOWED_WARNED: set = set()
#: suppression sites fire from supervisor/resolver threads as well as
#: the caller's — the registry mutation must not race
_SWALLOWED_LOCK = threading.Lock()


def note_swallowed(site: str, exc: BaseException, stats=None) -> None:
    """Record a deliberately suppressed exception.

    Increments ``SWALLOWED_ERRORS[site][type(exc).__name__]`` (under a
    module lock — suppression sites fire from background threads), bumps
    ``stats.swallowed_errors`` when an ``EngineCounters`` is supplied,
    and emits ONE ``RuntimeWarning`` per (site, exception class) per
    process — loud enough to see in logs, quiet enough not to spam a
    serving loop that hits the same broken cache on every lookup.
    Never raises (it guards suppression sites)."""
    try:
        cls = type(exc).__name__
        with _SWALLOWED_LOCK:
            by_cls = SWALLOWED_ERRORS.setdefault(site, {})
            by_cls[cls] = by_cls.get(cls, 0) + 1
            warn = (site, cls) not in _SWALLOWED_WARNED
            if warn:
                _SWALLOWED_WARNED.add((site, cls))
        if stats is not None:
            if hasattr(stats, "inc"):
                stats.inc("swallowed_errors")
            else:
                stats.swallowed_errors += 1
        if warn:
            warnings.warn(
                "suppressed %s at %s: %s (further occurrences counted "
                "in dpf_tpu.utils.profiling.SWALLOWED_ERRORS, not "
                "re-warned)" % (cls, site, exc), RuntimeWarning,
                stacklevel=3)
    except Exception:
        pass


def swallowed_snapshot() -> dict:
    """A JSON-ready copy of the swallowed-error registry (benchmark
    records embed it so suppressed causes are visible in artifacts)."""
    with _SWALLOWED_LOCK:
        return {site: dict(by_cls) for site, by_cls in
                sorted(SWALLOWED_ERRORS.items())}


class Timer:
    """Wall-clock block timer that blocks on device completion.

    The old exit barrier — ``block_until_ready(jnp.zeros(()))`` — only
    proves ONE fresh dispatch finished; on an asynchronous backend (TPU)
    independent prior computations may still be in flight, so the timer
    under-reported.  The exit now drains via ``jax.effects_barrier()``
    when the runtime has it (probed once through ``utils.compat``),
    else blocks on the outputs handed to ``note()``, and only as a last
    resort falls back to the legacy zeros sync."""

    def __init__(self, *outputs):
        self.elapsed = 0.0
        self._outputs = list(outputs)

    def note(self, *outputs) -> "Timer":
        """Register result arrays the exit barrier must block on when
        ``jax.effects_barrier`` is unavailable."""
        self._outputs.extend(outputs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import jax

        from . import compat
        # drain any async dispatch before stopping the clock
        if compat.has_effects_barrier():
            jax.effects_barrier()
        elif self._outputs:
            jax.block_until_ready(self._outputs)
        else:
            jax.block_until_ready(jax.numpy.zeros(()))
        self.elapsed = time.perf_counter() - self._t0
        return False
