"""Benchmark helpers: the printed-dict perf protocol.

Machine-readable result lines mirror the reference's protocol
(``dpf_gpu/dpf_benchmark.cu:307-314`` prints a Python dict per run;
``dpf.py:286-320`` measures wall-clock dpfs/sec over repeated batched
evals) so downstream tooling (sweeps, codesign joins) can scrape them.
"""

from __future__ import annotations

import json
import time

import numpy as np


def test_dpf_perf(N=16384, batch=512, entrysize=16, prf=None, reps=10,
                  keys_distinct=None, quiet=False, check=False,
                  config=None, dispatch_deadline=None):
    """Measure batched eval throughput; returns the result dict.

    Every key in the measured batch is a distinct real key by default
    (keygen is host-side and O(log N), so this costs seconds of setup and
    keeps the headline number beyond reproach); pass a smaller
    `keys_distinct` to tile instead — device work is identical per key.

    check=True verifies share recovery on the measured batch before timing
    (the role of the reference harness's DUMMY-gated check_correct,
    ``dpf_benchmark.cu:281-294`` — here exact for every PRF).
    """
    from ..api import DPF

    dpf = DPF(prf=prf, config=config)
    dpf.dispatch_deadline = dispatch_deadline
    if keys_distinct is None:
        keys_distinct = batch
    # odd multiplier is bijective mod the pow2 table size: indices are
    # distinct (for keys_distinct <= N) and well-spread at any batch size
    idxs = [(i * 0x9E3779B1) % N for i in range(keys_distinct)]
    pairs = [dpf.gen(i, N) for i in idxs]
    ks = [p[0] for p in pairs]
    keys = [ks[i % keys_distinct] for i in range(batch)]

    # generate directly at int32 width (an int64 intermediate would be an
    # 8.6 GB transient at the large-table sweep's N=2^26)
    table = np.random.default_rng(1).integers(
        0, 2 ** 31, (N, entrysize), dtype=np.int32, endpoint=False)
    dpf.eval_init(table)

    if check:
        a = np.asarray(dpf.eval_tpu(ks))
        b = np.asarray(dpf.eval_tpu([p[1] for p in pairs]))
        rec = (a - b).astype(np.int32)
        # explicit raise, not assert: the gate backs the "checked"
        # provenance field and must survive python -O
        if not (rec == table[idxs]).all():
            raise AssertionError("share recovery check failed")

    dpf.eval_tpu(keys)  # compile + warm
    tstart = time.time()
    for _ in range(reps):
        dpf.eval_tpu(keys)
    elapsed = time.time() - tstart

    result = {
        "entries": N,
        "batch_size": batch,
        "entry_size": entrysize,
        "prf": dpf.prf_method_string,
        "reps": reps,
        "elapsed_s": round(elapsed, 4),
        "dpfs_per_sec": int(batch * reps / elapsed),
        "key_size_bytes": 2096,
        "checked": bool(check),  # exact share-recovery gate ran pre-timing
    }
    if not quiet:
        print("%s Key Size: %d bytes, Perf: %d dpfs/sec"
              % (dpf, result["key_size_bytes"], result["dpfs_per_sec"]))
        print(json.dumps(result))
    return result


def test_dpf_latency(N=16384, entrysize=16, prf=None, reps=20, quiet=False,
                     config=None):
    """Single-query latency (the reference's latency benchmark mode,
    ``dpf_benchmark.cu:242-276``): one key, one dispatch, wall-clock ms."""
    from ..api import DPF

    dpf = DPF(prf=prf, config=config)
    k1, _ = dpf.gen(N // 3, N)
    table = np.random.default_rng(1).integers(
        0, 2 ** 31, (N, entrysize), dtype=np.int32, endpoint=False)
    dpf.eval_init(table)
    dpf.eval_tpu([k1])  # compile + warm
    t0 = time.time()
    for _ in range(reps):
        dpf.eval_tpu([k1])
    elapsed = time.time() - t0
    result = {
        "mode": "latency",
        "entries": N,
        "entry_size": entrysize,
        "prf": dpf.prf_method_string,
        "scheme": getattr(dpf, "scheme", "logn"),
        "reps": reps,
        "latency_ms": round(1e3 * elapsed / reps, 3),
    }
    if not quiet:
        print(json.dumps(result))
    return result


def test_matmul_perf(B=512, K=65536, E=16, reps=10, quiet=False):
    """Benchmark the contraction strategies alone (role of the reference's
    ``dpf_gpu/matmul_benchmark.cu``): [B,K] x [K,E] exact mod-2^32."""
    import jax
    import jax.numpy as jnp

    from ..ops import matmul128

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(-2 ** 31, 2 ** 31, (B, K),
                                 dtype=np.int64).astype(np.int32))
    b = jnp.asarray(rng.integers(-2 ** 31, 2 ** 31, (K, E),
                                 dtype=np.int64).astype(np.int32))
    results = {}
    for name, impl in matmul128.IMPLS.items():
        fn = jax.jit(impl)
        fn(a, b).block_until_ready()
        t0 = time.time()
        for _ in range(reps):
            out = fn(a, b)
        out.block_until_ready()
        elapsed = time.time() - t0
        r = {"impl": name, "B": B, "K": K, "E": E, "reps": reps,
             "elapsed_s": round(elapsed, 4),
             "gops_per_sec": round(2e-9 * B * K * E * reps / elapsed, 2)}
        results[name] = r
        if not quiet:
            print(json.dumps(r))
    return results
