"""Benchmark helpers: the printed-dict perf protocol.

Machine-readable result lines mirror the reference's protocol
(``dpf_gpu/dpf_benchmark.cu:307-314`` prints a Python dict per run;
``dpf.py:286-320`` measures wall-clock dpfs/sec over repeated batched
evals) so downstream tooling (sweeps, codesign joins) can scrape them.
"""

from __future__ import annotations

import json
import time

import numpy as np


def test_dpf_perf(N=16384, batch=512, entrysize=16, prf=None, reps=10,
                  keys_distinct=8, quiet=False):
    """Measure batched eval throughput; returns the result dict.

    Generates `keys_distinct` real keys and tiles them to `batch` (keygen is
    host-side and O(log N); tiling keeps setup time out of the measurement
    without changing device work, which is identical per key).
    """
    from ..api import DPF

    dpf = DPF(prf=prf)
    ks = [dpf.gen(int(i * (N // max(keys_distinct, 1))) % N, N)[0]
          for i in range(keys_distinct)]
    keys = [ks[i % keys_distinct] for i in range(batch)]

    table = np.random.randint(0, 2 ** 31, (N, entrysize),
                              dtype=np.int64).astype(np.int32)
    dpf.eval_init(table)

    dpf.eval_tpu(keys)  # compile + warm
    tstart = time.time()
    for _ in range(reps):
        dpf.eval_tpu(keys)
    elapsed = time.time() - tstart

    result = {
        "entries": N,
        "batch_size": batch,
        "entry_size": entrysize,
        "prf": dpf.prf_method_string,
        "reps": reps,
        "elapsed_s": round(elapsed, 4),
        "dpfs_per_sec": int(batch * reps / elapsed),
        "key_size_bytes": 2096,
    }
    if not quiet:
        print("%s Key Size: %d bytes, Perf: %d dpfs/sec"
              % (dpf, result["key_size_bytes"], result["dpfs_per_sec"]))
        print(json.dumps(result))
    return result
