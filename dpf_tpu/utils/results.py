"""Shared readers for the measurement results JSONL.

``experiments/tpu_all.py`` appends one record per measurement point to
``tpu_results.jsonl`` across rounds and retries; every record carries a
``sid`` (one per session process) and ``t`` (unix time).  Consumers
(``bench.py``, ``scripts/report.py``, ``experiments/
scaling_projection.py``) must not mix sessions or rounds: a stale fast
row from an earlier session/round would advertise numbers the current
code cannot reproduce and mask regressions.  The canonical scope is the
latest session that completed with data (``stage=="session"`` record
with ``done: true``) *within the current build round* (round boundary =
first PROGRESS.jsonl entry of the max round).
"""

from __future__ import annotations

import json
import os


def load_rows(path):
    """All well-formed dict records from a results JSONL (missing file
    or garbage lines -> skipped)."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if isinstance(r, dict):
                    rows.append(r)
    except OSError:
        pass
    return rows


def round_start_t(repo_dir=None):
    """Unix time the current build round started (first PROGRESS.jsonl
    entry of the max round), or None when the boundary is unknowable
    (no/unparsable PROGRESS.jsonl).  Callers FAIL CLOSED on None."""
    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    starts = {}
    try:
        with open(os.path.join(repo_dir, "PROGRESS.jsonl")) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    starts.setdefault(int(r["round"]), float(r["ts"]))
                except (ValueError, KeyError, TypeError):
                    continue
    except OSError:
        return None
    return starts[max(starts)] if starts else None


def _t(r):
    try:
        return float(r.get("t", 0))
    except (TypeError, ValueError):
        return 0.0


def latest_done_sid(rows, since=None):
    """sid of the newest completed session (``done: true``) at/after
    ``since``, else None."""
    sid = None
    for r in rows:
        if (r.get("stage") == "session" and r.get("done")
                and r.get("sid") is not None
                and (since is None or _t(r) >= since)):
            sid = r["sid"]
    return sid


def session_rows(rows, sid=None, since=None):
    """Rows of session ``sid`` (default: latest session completed
    at/after ``since``).  [] when none exists — consumers fail closed
    rather than mixing sessions or rounds.

    When ``since`` is given, rows timestamped before it are dropped even
    if they belong to the selected session: a session straddling the
    round boundary (started late in round N, completed in round N+1)
    must not leak pre-round measurements into "measured this round"
    consumers (bench.py's cache, report.py renderers)."""
    if sid is None:
        sid = latest_done_sid(rows, since=since)
    if sid is None:
        return []
    return [r for r in rows if r.get("sid") == sid
            and (since is None or _t(r) >= since)]
