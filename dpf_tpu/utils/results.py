"""Shared readers for the measurement results JSONL.

``experiments/tpu_all.py`` appends one record per measurement point to
``tpu_results.jsonl`` across rounds and retries; every record carries a
``sid`` (one per session process) and ``t`` (unix time).  Renderers
(``scripts/report.py``, ``experiments/scaling_projection.py``) must
present a SINGLE self-consistent session — mixing rows from different
sessions (different code versions, different rounds) can advertise a
stale best that the current code cannot reproduce.  The canonical scope
is the latest session that completed with data (its ``stage=="session"``
record has ``done: true``).
"""

from __future__ import annotations

import json


def load_rows(path):
    """All well-formed dict records from a results JSONL (missing file
    or garbage lines -> skipped)."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if isinstance(r, dict):
                    rows.append(r)
    except OSError:
        pass
    return rows


def latest_done_sid(rows):
    """sid of the newest session record with ``done: true``, else None."""
    sid = None
    for r in rows:
        if (r.get("stage") == "session" and r.get("done")
                and r.get("sid") is not None):
            sid = r["sid"]
    return sid


def session_rows(rows, sid=None):
    """Rows belonging to session ``sid`` (default: the latest completed
    session).  Returns [] when no completed session exists — renderers
    fail closed rather than mixing sessions."""
    if sid is None:
        sid = latest_done_sid(rows)
    if sid is None:
        return []
    return [r for r in rows if r.get("sid") == sid]
