"""Multi-tenant serving: per-tenant routers, weighted-fair dispatch.

One process, many tables, many tenants — the serving-stack layer the
ROADMAP's "multi-tenant fleet serving" item names.  ``TenantRouter``
composes the existing single-table machinery into an isolated
per-tenant stack over shared infrastructure:

* **One ``SchemeRouter`` per tenant** over that tenant's registry
  tables (``serve/registry.py`` holds the named, versioned,
  LRU-resident uploads), so every tenant keeps the full construction
  race, cost model, retry/failover, breakers, and supervisor rebuilds
  of the single-tenant path.
* **Shared where sharing is safe** — the persistent XLA compile cache
  and tuning cache are process-global already, and tenants whose
  (N, E, cap) shapes collide share ONE bucket ladder (the same
  ``Buckets`` instance, tuned once via ``lookup_router_knobs``), so a
  fourth tenant over an existing shape adds zero new XLA programs.
* **Isolated where isolation is the point** — admission control
  (``LoadShed``), ``CircuitBreaker`` state, ``RetryPolicy``, fault
  injectors, and SLOs are all per-tenant: an open breaker or shed
  storm in one tenant never touches another tenant's queue, and every
  flight/metrics event the per-tenant stack emits carries ``tenant=``.
* **Weighted-fair scheduling** — a deficit-round-robin scheduler over
  the per-tenant pending queues (``weight`` = share of dispatch,
  ``max_in_flight`` = per-tenant concurrency quota).  A bursting
  tenant accumulates backlog in ITS queue and is clipped to its
  weighted share + quota; other tenants' batches keep dispatching at
  their share.  Deficit is denominated in queries, so weights divide
  throughput, not batch counts.
* **Per-tenant dispatch workers** — DRR grants are *executed* on one
  worker thread per tenant, never on the granting caller's thread.
  ``submit_resilient`` can legitimately stall inside a single grant
  (retry backoff sleeps, failover re-dispatches, an injected fault
  storm), and executing it under the scheduler lock — or inline on
  whatever thread happened to pump — would hand one tenant's stall to
  every other tenant's submit path.  The scheduler lock is only ever
  held for queue/quota bookkeeping.

The noisy-neighbor chaos bench (``serve/bench_multitenant.py``,
``benchmark.py --multitenant``) gates the isolation claim: a victim
tenant absorbs a 4x burst plus a seeded ``FaultPlan`` while every
other tenant's availability and p99 hold at its solo baseline.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from ..obs.flight import FLIGHT
from ..utils.profiling import note_swallowed
from .buckets import Buckets
from .engine import EngineClosed, LoadShed
from .registry import TableRegistry
from .router import LABELS, SchemeRouter

#: default deficit-round-robin quantum (queries credited per round at
#: weight 1.0) — one cap-sized batch per round for the default ladder
QUANTUM = 128


@dataclasses.dataclass
class TenantSpec:
    """One tenant's serving contract.

    ``table`` registers a new table under ``name`` at ``add_tenant``
    time; ``table_name`` instead points at an existing registry name
    (two tenants MAY serve the same table).  ``weight`` is the DRR
    share; ``max_in_flight`` bounds dispatched-but-unresolved batches
    (the concurrency quota that stops a burst from monopolizing the
    device); ``max_queue_depth`` + ``shed`` arm tenant-level admission
    control, and ``slo_s``/``shed`` also arm the per-engine p99
    admission of the single-tenant path.  ``plan`` is an optional
    per-tenant ``FaultPlan`` (chaos testing: the injector is private to
    this tenant's engines)."""
    name: str
    table: object = None
    table_name: str | None = None
    weight: float = 1.0
    slo_s: float | None = None
    max_in_flight: int = 4
    max_queue_depth: int | None = None
    shed: bool = False
    cap: int = 128
    plan: object = None
    retry: object = None
    breaker_failures: int = 5
    breaker_reset_s: float = 30.0
    probe: bool = True

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError("weight must be > 0 (got %r)"
                             % (self.weight,))
        if self.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1 (got %d)"
                             % self.max_in_flight)


class _PendingBatch:
    __slots__ = ("batch", "keys_for", "arrival", "future")

    def __init__(self, batch, keys_for, arrival, future):
        self.batch = batch
        self.keys_for = keys_for
        self.arrival = arrival
        self.future = future


class TenantFuture:
    """Result handle for one tenant batch: queued (DRR backlog) ->
    dispatched (engine future in flight) -> resolved (value or error).

    ``result()`` pumps the scheduler while queued — within a tenant,
    batches dispatch and resolve FIFO, so waiting on a queued batch
    first resolves the tenant's older in-flight ones (freeing quota)
    until this one dispatches."""

    __slots__ = ("_sched", "_tenant", "_routed", "_lease", "_value",
                 "_exc", "_state")

    def __init__(self, sched, tenant):
        self._sched = sched
        self._tenant = tenant
        self._routed = None
        self._lease = None
        self._value = None
        self._exc = None
        self._state = "queued"

    @property
    def tenant(self) -> str:
        return self._tenant.name

    @property
    def decision(self):
        """The routing decision that served this batch (None until
        dispatched)."""
        return getattr(self._routed, "decision", None)

    def done(self) -> bool:
        return self._state == "resolved"

    def _resolve(self) -> None:
        """Resolve the underlying engine future; stores value/error,
        never raises (errors surface at ``result()``)."""
        t = self._tenant
        with t.elock:
            if self._state == "resolved":
                return
            if self._state != "dispatched":
                raise RuntimeError("cannot resolve a queued batch")
            try:
                self._value = self._routed.result()
            except Exception as e:
                self._exc = e
            self._state = "resolved"
        if self._lease is not None:
            self._lease.release()
            self._lease = None
        self._sched._on_resolved(t, self)

    def result(self):
        while self._state == "queued":
            self._sched.pump()
            if self._state != "queued":
                break
            head = self._sched._oldest_in_flight(self._tenant)
            if head is not None and head is not self:
                head._resolve()      # frees quota; FIFO within tenant
            else:
                time.sleep(2e-4)     # grant is on the tenant's worker
        if self._state == "dispatched":
            self._resolve()
        if self._exc is not None:
            raise self._exc
        return self._value


class _Tenant:
    """Scheduler-side state for one tenant."""

    __slots__ = ("spec", "router", "lease0", "queue", "grants",
                 "inflight", "in_flight", "deficit", "submitted",
                 "dispatched", "shed_batches", "shed_queries",
                 "quota_defers", "errors", "elock", "cv", "stopped",
                 "worker")

    def __init__(self, spec, router, lease0):
        self.spec = spec
        self.router = router
        self.lease0 = lease0          # warmup-time pin (released after)
        self.queue = deque()          # _PendingBatch, FIFO (pre-grant)
        self.grants = deque()         # DRR-granted, awaiting the worker
        self.inflight = deque()       # dispatched unresolved futures
        self.in_flight = 0
        self.deficit = 0.0
        self.submitted = 0
        self.dispatched = 0
        self.shed_batches = 0
        self.shed_queries = 0
        self.quota_defers = 0
        self.errors = 0
        self.elock = threading.RLock()  # serializes THIS tenant's engines
        self.cv = threading.Condition()  # wakes THIS tenant's worker
        self.stopped = False
        self.worker = None

    @property
    def name(self) -> str:
        return self.spec.name


class TenantRouter:
    """Per-tenant ``SchemeRouter``s + registry residency + DRR dispatch.

    Args:
      registry: a ``TableRegistry`` to serve from (one is created when
        None — ``budget_bytes``/``prf_method`` configure it).
      quantum: DRR credit (queries) granted per round at weight 1.0.

    ``add_tenant(spec)`` builds the tenant's stack; ``submit(name,
    batch, keys_for, arrival=None)`` enqueues one batch and returns a
    ``TenantFuture`` (or raises ``LoadShed`` when the tenant's own
    admission control rejects it — never because of another tenant's
    state).  Dispatch order across tenants is deficit-round-robin; each
    dispatch pins the tenant's table version in the registry for the
    life of the batch, so LRU eviction pressure can never demote a
    table out from under an in-flight query.
    """

    def __init__(self, registry: TableRegistry | None = None, *,
                 budget_bytes: int | None = None, prf_method: int = 0,
                 quantum: int = QUANTUM):
        self.registry = registry if registry is not None else \
            TableRegistry(budget_bytes, prf_method=prf_method)
        self.quantum = float(quantum)
        self.tenants = {}             # name -> _Tenant
        self._ladders = {}            # (n, e, cap) -> (Buckets, knobs)
        self._closed = False          # close() ran; submit rejects
        self._lock = threading.RLock()
        try:
            from ..obs.metrics import register_tenants
            register_tenants(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.tenant.register_metrics", e)

    # -------------------------------------------------------- tenants

    def add_tenant(self, spec: TenantSpec, *, version: int | None = None
                   ) -> "_Tenant":
        """Register (or attach to) the tenant's table and build its
        router over the registry's prepared servers.  Shapes that
        collide with an existing tenant share that tenant's bucket
        ladder (the identical ``Buckets`` instance)."""
        with self._lock:
            if spec.name in self.tenants:
                raise ValueError("tenant %r already added" % spec.name)
            table_name = spec.table_name or spec.name
            if spec.table is not None:
                self.registry.register(table_name, spec.table,
                                       version=version)
            # hold a pin across router construction: warmup/probe
            # dispatches must not race an eviction of this very table
            lease = self.registry.acquire(table_name, version=version)
            ladder, knobs = self._ladder(lease.servers, spec.cap)
            injector = (spec.plan.injector()
                        if spec.plan is not None else None)
            router = SchemeRouter(
                None, servers=lease.servers, cap=spec.cap,
                buckets=ladder,
                max_in_flight=int(knobs.get("max_in_flight", 2)),
                ewma_alpha=float(knobs.get("ewma_alpha", 0.25)),
                probe=spec.probe, slo_s=spec.slo_s,
                max_queue_depth=spec.max_queue_depth, shed=spec.shed,
                injector=injector, retry=spec.retry,
                breaker_failures=spec.breaker_failures,
                breaker_reset_s=spec.breaker_reset_s,
                supervise=True, tenant=spec.name)
            t = _Tenant(spec, router, lease)
            t.lease0.release()        # steady state pins per dispatch
            t.worker = threading.Thread(
                target=self._worker, args=(t,), daemon=True,
                name="dpf-tenant-%s" % spec.name)
            t.worker.start()
            self.tenants[spec.name] = t
            FLIGHT.record("tenant", action="add", tenant=spec.name,
                          table=table_name, weight=spec.weight,
                          max_in_flight=spec.max_in_flight)
            return t

    def _ladder(self, servers, cap: int):
        """One bucket ladder per (N, E, cap) shape, shared across every
        tenant whose shape collides (comparable per-bucket costs AND
        zero extra XLA programs for the shared shapes)."""
        srv = next(iter(servers.values()))
        key = (srv.table_num_entries, srv.table_effective_entry_size,
               int(cap))
        hit = self._ladders.get(key)
        if hit is not None:
            return hit
        knobs = None
        try:
            from ..tune.serve_tune import lookup_router_knobs
            shape = type("Shape", (), {
                "n": key[0], "entry_size": key[1],
                "prf_method": srv.prf_method})()
            knobs = lookup_router_knobs(shape, cap)
        except Exception as e:  # tuned ladder is an optimization only
            note_swallowed("serve.tenant.ladder_lookup", e)
        buckets = Buckets(knobs["buckets"] if knobs
                          else Buckets.default_sizes(cap))
        self._ladders[key] = (buckets, knobs or {})
        return self._ladders[key]

    def router(self, name: str) -> SchemeRouter:
        return self.tenants[name].router

    # --------------------------------------------------------- submit

    def submit(self, name: str, batch: int, keys_for, *,
               arrival: int | None = None) -> TenantFuture:
        """Enqueue one batch for ``name``; DRR decides when it
        dispatches.  Tenant-level admission runs here: over
        ``max_queue_depth`` with ``shed=True`` the batch is rejected
        (``LoadShed``) — a decision made entirely from THIS tenant's
        queue state.  Engine-level sheds/faults during the eventual
        dispatch surface on the returned future's ``result()``."""
        with self._lock:
            if self._closed:
                raise EngineClosed(
                    "TenantRouter is closed — submit after close()")
            t = self.tenants[name]
            depth = len(t.queue) + t.in_flight
            if (t.spec.shed and t.spec.max_queue_depth is not None
                    and depth >= t.spec.max_queue_depth):
                t.shed_batches += 1
                t.shed_queries += batch
                FLIGHT.record("shed", engine="tenant-sched",
                              tenant=name, batch=batch,
                              reason="tenant_queue_depth",
                              pending=depth,
                              max_queue_depth=t.spec.max_queue_depth)
                raise LoadShed(
                    "tenant %r admission rejected the batch "
                    "(depth=%d >= %d)"
                    % (name, depth, t.spec.max_queue_depth))
            fut = TenantFuture(self, t)
            t.queue.append(_PendingBatch(batch, keys_for, arrival, fut))
            t.submitted += 1
        self.pump()
        return fut

    # ------------------------------------------------------ scheduling

    def pump(self) -> int:
        """Run deficit-round-robin *grant* rounds until every queued
        batch is either granted or quota-blocked; returns the number of
        batches granted.  Each round credits every backlogged,
        quota-unblocked tenant ``quantum * weight`` queries of deficit
        and grants its head batches while they fit — so a bursting
        tenant's backlog drains at its weighted share while small
        tenants' batches never wait behind it.  A grant reserves the
        tenant's quota and hands the batch to that tenant's dispatch
        worker; the scheduler lock is never held across engine work, so
        one tenant's retry storm cannot block another tenant's
        submit/pump path."""
        total = 0
        woken = []
        with self._lock:
            while True:
                eligible = [t for t in self.tenants.values() if t.queue]
                if not eligible:
                    break
                progress = False
                blocked = 0
                for t in eligible:
                    if t.in_flight >= t.spec.max_in_flight:
                        t.quota_defers += 1
                        blocked += 1
                        continue
                    t.deficit += self.quantum * t.spec.weight
                    while (t.queue
                           and t.queue[0].batch <= t.deficit
                           and t.in_flight < t.spec.max_in_flight):
                        pb = t.queue.popleft()
                        t.deficit -= pb.batch
                        t.in_flight += 1   # reserved at grant time
                        t.grants.append(pb)
                        if t not in woken:
                            woken.append(t)
                        progress = True
                        total += 1
                    if not t.queue:
                        t.deficit = 0.0   # no banked credit while idle
                if not progress and blocked == len(eligible):
                    break                 # all backlog is quota-blocked
        for t in woken:
            with t.cv:
                t.cv.notify()
        return total

    def _worker(self, t: "_Tenant") -> None:
        """Per-tenant dispatch loop: executes DRR grants under the
        tenant's OWN engine lock on the tenant's OWN thread."""
        while True:
            with t.cv:
                while not t.grants and not t.stopped:
                    t.cv.wait()
                if t.stopped and not t.grants:
                    return
            self._drain_grants(t)

    def _drain_grants(self, t: "_Tenant") -> None:
        freed = 0
        with t.elock:
            while t.grants:
                if not self._dispatch(t, t.grants.popleft()):
                    freed += 1
        if freed:
            with self._lock:
                t.in_flight = max(0, t.in_flight - freed)
            self.pump()               # freed quota: grant more backlog

    def _dispatch(self, t: "_Tenant", pb: _PendingBatch) -> bool:
        """One DRR-granted dispatch through the tenant's router (runs
        on the tenant's worker under ``t.elock``).  Pins the table
        version for the batch's lifetime; engine sheds/faults resolve
        the future with the error instead of raising here (another
        tenant must never see this tenant's failure).  Returns False
        when the grant died here (its quota reservation is released by
        the caller)."""
        fut = pb.future
        try:
            lease = self.registry.acquire(t.spec.table_name
                                          or t.spec.name)
            try:
                if (t.router.injector is not None
                        and pb.arrival is not None):
                    t.router.injector.begin_arrival(pb.arrival)
                routed = t.router.submit_resilient(pb.batch,
                                                   pb.keys_for)
            except BaseException:
                lease.release()
                raise
        except Exception as e:
            if isinstance(e, LoadShed):
                t.shed_batches += 1
                t.shed_queries += pb.batch
            else:
                t.errors += 1
            fut._exc = e
            fut._state = "resolved"
            return False
        fut._routed = routed
        fut._lease = lease
        fut._state = "dispatched"
        t.inflight.append(fut)
        t.dispatched += 1
        return True

    def _oldest_in_flight(self, t: "_Tenant"):
        # list() snapshots atomically under the GIL — the tenant's
        # worker appends to t.inflight without holding self._lock
        for f in list(t.inflight):
            if not f.done():
                return f
        return None

    def _on_resolved(self, t: "_Tenant", fut: TenantFuture) -> None:
        with self._lock:
            try:
                t.inflight.remove(fut)
            except ValueError:
                pass
            t.in_flight = max(0, t.in_flight - 1)
        self.pump()                   # freed quota: dispatch backlog

    # -------------------------------------------------------- plumbing

    def drain(self) -> None:
        """Dispatch and resolve every outstanding batch."""
        while True:
            self.pump()
            pending = []
            with self._lock:
                for t in self.tenants.values():
                    pending.extend(f for f in list(t.inflight)
                                   if not f.done())
                backlog = any(t.queue or t.grants
                              for t in self.tenants.values())
            if not pending and not backlog:
                return
            for f in pending:
                f._resolve()
            if not pending:
                time.sleep(2e-4)      # grants are on tenant workers

    def close(self) -> None:
        """Stop the per-tenant dispatch workers (outstanding grants are
        drained first).  Not usable afterwards: a later ``submit``
        raises ``EngineClosed`` (the same clean post-drain rejection
        the engines give, ``serve/engine.py``) instead of deadlocking
        against the stopped workers.  Idempotent."""
        self.drain()
        with self._lock:
            self._closed = True
        for t in self.tenants.values():
            with t.cv:
                t.stopped = True
                t.cv.notify()
        for t in self.tenants.values():
            if t.worker is not None:
                t.worker.join(timeout=5.0)

    def stats(self) -> dict:
        """Per-tenant scheduler + router diagnostics (benchmark
        records embed it), plus the registry residency snapshot."""
        with self._lock:
            out = {"quantum": self.quantum, "tenants": {}}
            for name, t in self.tenants.items():
                out["tenants"][name] = {
                    "weight": t.spec.weight,
                    "max_in_flight": t.spec.max_in_flight,
                    "submitted": t.submitted,
                    "dispatched": t.dispatched,
                    "shed_batches": t.shed_batches,
                    "shed_queries": t.shed_queries,
                    "quota_defers": t.quota_defers,
                    "errors": t.errors,
                    "queue_depth": len(t.queue),
                    "granted_pending": len(t.grants),
                    "in_flight": t.in_flight,
                    "router": t.router.stats(),
                }
            out["registry"] = self.registry.stats()
            return out

    def __repr__(self):
        return ("TenantRouter(%d tenants, quantum=%g)"
                % (len(self.tenants), self.quantum))
