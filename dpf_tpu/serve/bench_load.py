"""Open-loop load benchmark: cost-model scheme router vs sticky baseline.

``benchmark.py --load``.  Replays one seeded bursty mixed-shape arrival
trace (``serve/loadgen.py``) through two serving stacks over the same
table and reports full SLO accounting for each:

* **sticky** — one ``ServingEngine`` over the construction a
  ``DPF(scheme="auto")`` deployment would pin: the cached
  ``--autotune-scheme`` winner when the tuning cache is warm, else the
  conservative heuristic (binary GGM).  This is today's production
  path.
* **router** — ``serve.router.SchemeRouter``: per-arrival construction
  choice by the live cost model (probe-seeded, EWMA-updated).

The replay is **open-loop**: arrivals fire at their scheduled
timestamps whether or not the server kept up, so a stack slower than
the offered load accumulates a backlog and its latencies grow — per-
arrival latency is measured completion − *scheduled arrival*, the
client's-eye SLO number.  The trace's burst rate is chosen to exceed
the sticky construction's service capacity while staying under the
router's, which is exactly the regime the ROADMAP item names ("bursty,
heavy-tailed arrivals"): the sticky stack falls behind during bursts
(qps capped at its capacity, p99 inflated by queueing) while the
router absorbs them.

**Every routed answer is equality-gated against the scalar oracle**:
each pool key's reference share is computed once via ``DPF.eval_cpu``
(the host NumPy/native path) and every served batch — sticky and
routed — must match its reference rows bit-exactly; rejections are
counted in the record (an acceptance criterion is 0).

A third **shed leg** re-runs the router with admission control armed
(``slo_s`` + ``max_queue_depth``, ``shed=True``) under a deliberately
overloading trace, demonstrating bounded p99 at the cost of counted
sheds.  The committed CPU record is ``BENCH_LOAD_r10.json``; the same
command produces the relay-TPU record.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --load [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json
import time
from collections import deque

import numpy as np

from ..obs import FLIGHT, record_sections
from ..obs.tracer import span
from ..utils.profiling import quantile
from .engine import LoadShed, ServingEngine
from . import loadgen


def _key_pool(srv, n: int, distinct: int, tag: bytes):
    """``distinct`` server-0 keys for ``srv`` + their scalar-oracle
    reference shares (one ``eval_cpu`` call — the host NumPy/native
    path, the same oracle every tuner gate uses)."""
    keys = [srv.gen((i * 0x9E3779B1) % n, n, seed=tag + b"-%d" % i)[0]
            for i in range(distinct)]
    refs = np.asarray(srv.eval_cpu(keys))      # [distinct, E]
    return keys, refs


def _batch_for(pool, j: int, b: int):
    """Deterministic rotating view of the key pool: arrival j's batch
    of b keys and their pool indices (for the reference lookup)."""
    keys, _ = pool
    idxs = [(j + i) % len(keys) for i in range(b)]
    return [keys[i] for i in idxs], idxs


def replay(trace, submit, *, window: int = 8):
    """Open-loop replay of ``trace`` through ``submit(arrival, j)``.

    ``submit`` returns a future (``.result()``) or raises ``LoadShed``.
    Arrivals are released at their scheduled ``t`` (sleeping when
    ahead; when behind, back-to-back — the backlog is the server's
    problem, as in production).  While ahead of schedule the replay
    resolves outstanding futures (the polling client), and never holds
    more than ``window`` unresolved — per-arrival latency is
    completion − scheduled arrival, in seconds.

    One honesty note: the client is single-threaded, so a blocking
    ``result()`` in the idle gap can delay a later arrival's submit
    past its schedule.  The delay still lands in the MEASURED latency
    (which is against the scheduled time, not the actual submit), and
    both race legs replay through this identical loop, so the
    comparison is fair — but shed counts under overload are a floor
    (a threaded client would have offered, and shed, sooner).

    Returns ``(latencies, per_arrival, makespan_s, shed_batches,
    shed_queries)`` where ``per_arrival`` is ``(arrival, j, future)``
    for the equality gate (shed arrivals excluded).
    """
    t0 = time.perf_counter()
    outstanding = deque()               # (arrival, j, fut)
    done = []                           # (arrival, j, fut)
    lats = []
    sheds = shed_q = 0

    def resolve_oldest():
        a, j, fut = outstanding.popleft()
        fut.result()
        lats.append((time.perf_counter() - t0) - a.t)
        done.append((a, j, fut))

    for j, a in enumerate(trace):
        while True:
            now = time.perf_counter() - t0
            if now >= a.t:
                break
            if outstanding:             # use the idle gap to poll
                resolve_oldest()
            else:
                time.sleep(min(a.t - now, 0.02))
        while len(outstanding) >= window:
            resolve_oldest()
        try:
            fut = submit(a, j)
        except LoadShed:
            sheds += 1
            shed_q += a.batch
            continue
        outstanding.append((a, j, fut))
    while outstanding:
        resolve_oldest()
    return lats, done, time.perf_counter() - t0, sheds, shed_q


def _slo_stats(lats, slo_s: float) -> dict:
    if not lats:    # empty trace / everything shed: report, don't crash
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None,
                "max_ms": None, "deadline_miss_batches": 0,
                "deadline_miss_rate": 0.0}
    ms = sorted(x * 1e3 for x in lats)
    miss = sum(1 for x in lats if x > slo_s)
    return {
        "p50_ms": round(quantile(ms, 0.50, presorted=True), 3),
        "p95_ms": round(quantile(ms, 0.95, presorted=True), 3),
        "p99_ms": round(quantile(ms, 0.99, presorted=True), 3),
        "max_ms": round(ms[-1], 3),
        "deadline_miss_batches": miss,
        "deadline_miss_rate": round(miss / len(lats), 4),
    }


def _gate(done, pools, label_of) -> int:
    """Bit-exact equality of every served batch against the scalar-
    oracle reference rows; returns the rejection count."""
    rejections = 0
    with span("gate", batches=len(done)):
        for a, j, fut in done:
            label = label_of(fut)
            _, refs = pools[label]
            _, idxs = _batch_for(pools[label], j, a.batch)
            if not np.array_equal(fut.result(), refs[idxs]):
                rejections += 1
    return rejections


def load_bench(n=4096, entry_size=16, cap=128, prf=0, *,
               trace=None, seed=11, duration_s=7.0, on_rate=320.0,
               slo_ms=250.0, reps=2, distinct=16, window=8,
               shed_leg=True, quiet=False) -> dict:
    """Race the cost-model router against the sticky baseline on one
    seeded open-loop bursty trace; returns the ``--load`` record."""
    from .router import LABELS, SchemeRouter, resolve_sticky

    FLIGHT.clear()      # scope the embedded flight tail to this bench
    table = np.random.default_rng(seed ^ 0x10ad).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    if trace is None:
        trace = loadgen.bursty_trace(
            on_rate=on_rate, off_rate=2.0, on_s=1.0, off_s=2.0,
            duration_s=duration_s, cap=cap, seed=seed, n=n)
    total_q = loadgen.total_queries(trace)
    slo_s = slo_ms / 1e3

    # ---- stacks: router (3 constructions) + sticky single engine ----
    router = SchemeRouter(table, prf=prf, cap=cap, probe=True)
    # the ONE sticky-resolution rule, shared with the router's fallback
    sticky_label, sticky_from = resolve_sticky(n, entry_size, prf, cap)
    sticky_srv = router.server(sticky_label)     # same table upload
    sticky_engine = ServingEngine(sticky_srv, max_in_flight=2,
                                  buckets=router.buckets, warmup=True)
    pools = {lb: _key_pool(router.server(lb), n, distinct,
                           b"load-%s" % lb.encode())
             for lb in LABELS}

    def sticky_submit(a, j):
        keys, _ = _batch_for(pools[sticky_label], j, a.batch)
        return sticky_engine.submit(keys)

    def router_submit(a, j):
        dec = router.route(a.batch)
        keys, _ = _batch_for(pools[dec.construction], j, a.batch)
        return router.submit(dec, keys)

    def run_leg(submit, reset, stats_fn) -> tuple:
        """Best-qps rep; ``stats_fn()`` is snapshotted per rep so the
        record's counters describe the SAME run as its qps/latencies."""
        best = None
        for _ in range(max(1, reps)):
            reset()
            lats, done, makespan, sheds, shed_q = replay(
                trace, submit, window=window)
            qps = int((total_q - shed_q) / makespan)
            if best is None or qps > best[0]:
                best = (qps, lats, done, makespan, stats_fn())
        return best

    # ---- sticky leg --------------------------------------------------
    q_s, lats_s, done_s, mk_s, stats_s = run_leg(
        sticky_submit, sticky_engine.stats.reset,
        lambda: sticky_engine.stats.as_dict())
    sticky_leg = {
        "construction": sticky_label, "resolved_from": sticky_from,
        "qps": q_s, "makespan_s": round(mk_s, 4),
        "served_queries": total_q,
        **_slo_stats(lats_s, slo_s),
        "engine_stats": stats_s,
    }

    # ---- router leg --------------------------------------------------
    q_r, lats_r, done_r, mk_r, stats_r = run_leg(
        router_submit, router.reset_counters, router.stats)
    router_leg = {
        "qps": q_r, "makespan_s": round(mk_r, 4),
        "served_queries": total_q,
        **_slo_stats(lats_r, slo_s),
        "router_stats": stats_r,
    }

    # ---- shed leg first: its served batches are gated too ------------
    shed_rec = None
    if shed_leg:
        servers = {lb: router.server(lb) for lb in router.constructions}
        shed_rec = _shed_leg(servers, cap, trace, pools, slo_s, window)

    # ---- equality gate (post-timing; futures cache their results) ----
    rejections = _gate(done_s, pools, lambda f: sticky_label)
    rejections += _gate(done_r, pools,
                        lambda f: f.decision.construction)
    if shed_rec is not None:
        rejections += shed_rec["gate_rejections"]

    record = {
        "metric": "traffic-shaped serving: cost-model scheme router vs "
                  "sticky cached-winner engine (entries=%d, "
                  "entry_size=%d, prf=%d, bursty open-loop trace: %d "
                  "arrivals / %d queries, cap=%d, slo=%dms, 1 device)"
                  % (n, entry_size, prf, len(trace), total_q, cap,
                     int(slo_ms)),
        "value": q_r,
        "unit": "queries/sec",
        "vs_baseline": round(q_r / q_s, 4) if q_s else None,
        "baseline": "sticky-scheme ServingEngine (the DPF(scheme="
                    "'auto') resolution: cached --autotune-scheme "
                    "winner, else the binary-GGM heuristic) on the "
                    "identical seeded trace and key pools",
        "p99_vs_baseline": round(router_leg["p99_ms"]
                                 / sticky_leg["p99_ms"], 4)
        if sticky_leg["p99_ms"] and router_leg["p99_ms"] is not None
        else None,
        "slo_ms": slo_ms,
        "trace": {"kind": "bursty", "seed": seed,
                  "duration_s": duration_s, "on_rate": on_rate,
                  "arrivals": len(trace), "queries": total_q,
                  "cap": cap, "reps": reps, "window": window},
        "sticky": sticky_leg,
        "router": router_leg,
        # the live EWMA cost model after the race — the digital twin's
        # service-time input (plan/twin.py); embedding it makes every
        # downstream twin run auditable against this record
        "cost_table": router.cost_table(),
        "gate_rejections": rejections,
        "checked": rejections == 0,  # every served batch matched the
        #                              scalar oracle (DPF.eval_cpu)
    }

    if shed_rec is not None:
        record["shed_leg"] = shed_rec
    record["obs"] = record_sections()
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def _shed_leg(servers, cap, trace, pools, slo_s, window) -> dict:
    """Router with admission control armed on a compressed (4x rate)
    copy of the trace — offered load well past even the router's
    capacity: p99 of ADMITTED arrivals stays bounded, the overload
    shows up as counted sheds instead of unbounded queueing.  Reuses
    the main router's prepared servers (no second table upload /
    warmup compile — the engines' admission knobs are the only
    difference)."""
    from .router import SchemeRouter
    router = SchemeRouter(None, servers=servers, cap=cap, probe=True,
                          slo_s=slo_s, max_queue_depth=max(2, window // 2),
                          shed=True)
    squeezed = loadgen.squeeze(trace, 4.0)

    def submit(a, j):
        dec = router.route(a.batch)
        keys, _ = _batch_for(pools[dec.construction], j, a.batch)
        return router.submit(dec, keys)

    lats, done, makespan, sheds, shed_q = replay(squeezed, submit,
                                                 window=window)
    counters = router.counters()
    return {
        "qps_admitted": int((loadgen.total_queries(squeezed) - shed_q)
                            / makespan),
        "makespan_s": round(makespan, 4),
        "shed_batches": sheds, "shed_queries": shed_q,
        **_slo_stats(lats, slo_s),
        "engine_shed_batches": counters.shed_batches,
        "slo_s": slo_s,
        # the ADMITTED batches are gated like the main legs (the
        # docstring's every-served-batch promise includes this leg)
        "gate_rejections": _gate(done, pools,
                                 lambda f: f.decision.construction),
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--duration", type=float, default=7.0,
                    help="trace duration in seconds")
    ap.add_argument("--on-rate", type=float, default=320.0,
                    help="burst arrival rate (arrivals/sec in ON "
                         "windows)")
    ap.add_argument("--slo-ms", type=float, default=250.0)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--no-shed-leg", action="store_true")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): exercises every "
                         "leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.dryrun:
        record = load_bench(n=512, entry_size=8, cap=16, prf=args.prf,
                            seed=args.seed, duration_s=1.5,
                            on_rate=30.0, slo_ms=args.slo_ms, reps=1,
                            distinct=8, shed_leg=not args.no_shed_leg)
    else:
        record = load_bench(n=args.n, entry_size=args.entry_size,
                            cap=args.cap, prf=args.prf, seed=args.seed,
                            duration_s=args.duration,
                            on_rate=args.on_rate, slo_ms=args.slo_ms,
                            reps=args.reps,
                            shed_leg=not args.no_shed_leg)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
