"""Multichip rehearsal benchmark: the whole mesh matrix, tuned vs
heuristic, equality-gated.

``benchmark.py --multichip`` runs every (construction x mesh split x
shape) cell of the scale-out path through the mesh autotuner
(``tune.mesh_tune``): per cell the mesh heuristic opener and every
searched candidate are equality-gated against the scalar host oracle
(bit-identical [B, E] shares — that IS the correctness matrix, a
rejected candidate is recorded and never timed), the per-shape split
winner is raced (``tune_mesh_shape``, warm-cache from the matrix), and
the serving-engine ladder is tuned on the winning split's batch axis
(``tune_mesh_serving``).  One self-describing JSON record comes out —
committed as ``MULTICHIP_r06.json`` for the forced-8-device CPU
rehearsal; the SAME command with ``--native`` uses the real device mesh
on the relay and produces the TPU record (the fingerprint tells the
records apart).

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --multichip [--out MULTICHIP_r06.json]
"""

from __future__ import annotations

import json
import time


DEFAULT_SHAPES = ((2048, 8), (8192, 32))

#: (scheme, radix, label) — the same three constructions the
#: single-device scheme sweep races (search.CONSTRUCTIONS)
CONSTRUCTIONS = (("logn", 2, "logn"), ("logn", 4, "radix4"),
                 ("sqrtn", 2, "sqrtn"))


def multichip_bench(shapes=DEFAULT_SHAPES, *, n_devices: int = 8,
                    native: bool = False, prf: int = 1,
                    entry_size: int = 16, reps: int = 2,
                    force: bool = False, out: str | None = None,
                    quiet: bool = False) -> dict:
    """Run the rehearsal matrix and return (and optionally write) the
    record.  ``native=False`` forces ``n_devices`` virtual CPU devices
    before any backend init (``utils.hermetic.force_cpu_mesh`` — the
    same recipe as tests/conftest.py, so the run is hermetic against a
    wedged TPU relay); ``native=True`` keeps whatever devices the
    backend exposes (the relay path)."""
    if not native:
        from ..utils.hermetic import force_cpu_mesh
        force_cpu_mesh(n_devices)
    import jax

    from ..core.prf_ref import PRF_NAMES
    from ..parallel.sharded import make_mesh
    from ..tune import compcache
    from ..tune.cache import default_cache
    from ..tune.fingerprint import device_fingerprint
    from ..tune.mesh_tune import (mesh_split_candidates, tune_mesh_eval,
                                  tune_mesh_serving, tune_mesh_shape)
    from ..utils.profiling import CACHE_COUNTERS

    compcache.enable()
    cache = default_cache()
    devices = jax.devices()
    n_devices = len(devices) if native else min(n_devices, len(devices))
    log = None if quiet else (lambda m: print(m, flush=True))
    splits = mesh_split_candidates(n_devices)

    t_start = time.perf_counter()
    points = []
    total_rejected = 0
    for n, batch in shapes:
        constructions = []
        for scheme, radix, label in CONSTRUCTIONS:
            rows = []
            for nb, nt in splits:
                mesh = make_mesh(n_table=nt, n_batch=nb,
                                 devices=devices[:n_devices])
                if log:
                    log("tuning %s n=%d batch=%d mesh=%dx%d ..."
                        % (label, n, batch, nb, nt))
                try:
                    rec = tune_mesh_eval(
                        n, batch, mesh=mesh, entry_size=entry_size,
                        prf_method=prf, scheme=scheme, radix=radix,
                        reps=reps, cache=cache, force=force, log=log)
                except AssertionError:
                    raise  # oracle mismatch: a correctness bug — abort
                except Exception as exc:
                    # split invalid for this construction (e.g. a
                    # sqrt-N grid whose rows don't divide over the
                    # shards): record the cell, keep the matrix going
                    if log:
                        log("  invalid split: %s" % exc)
                    rows.append({"mesh": "%dx%d" % (nb, nt),
                                 "invalid": str(exc)})
                    continue
                m = rec["measured"]
                total_rejected += m["rejected"]
                rows.append({
                    "mesh": m["mesh"],
                    "tuned_knobs": rec["knobs"],
                    "heuristic_knobs": rec["heuristic"],
                    "tuned_s": m["best_s"],
                    "heuristic_s": m["heuristic_s"],
                    "speedup_vs_heuristic": m["speedup_vs_heuristic"],
                    "tuned_qps": int(batch / m["best_s"]),
                    "heuristic_qps": int(batch / m["heuristic_s"]),
                    "candidates_tried": m["candidates_tried"],
                    "rejected": m["rejected"],
                    "from_cache": not rec["searched"],
                })
            row = {"construction": label, "scheme": scheme,
                   "radix": radix, "splits": rows}
            if any("tuned_s" in r for r in rows):
                # the split race re-reads the warm matrix entries
                # (free); force re-derives its winner record from the
                # cells this run just re-measured rather than serving a
                # stale one
                split_rec = tune_mesh_shape(
                    n, batch, devices=devices[:n_devices],
                    entry_size=entry_size, prf_method=prf, scheme=scheme,
                    radix=radix, reps=reps, cache=cache, force=force)
                row["winning_split"] = split_rec["knobs"]
            constructions.append(row)
        timed = [c for c in constructions
                 if any("tuned_s" in r for r in c["splits"])]
        if not timed:
            raise AssertionError(
                "no (construction, split) cell was valid at n=%d "
                "batch=%d on %d devices" % (n, batch, n_devices))
        best = min(
            timed,
            key=lambda c: min(r["tuned_s"] for r in c["splits"]
                              if "tuned_s" in r))
        points.append({"entries": n, "batch": batch,
                       "constructions": constructions,
                       "winner": best["construction"]})

    # serving-engine ladder on the mesh batch axis: largest point,
    # winning construction, its winning split
    head = max(points, key=lambda p: p["entries"] * p["batch"])
    n, batch = head["entries"], head["batch"]
    win_c = next(c for c in head["constructions"]
                 if c["construction"] == head["winner"])
    nb, nt = (win_c["winning_split"]["n_batch"],
              win_c["winning_split"]["n_table"])
    if log:
        log("tuning mesh serving ladder: %s n=%d cap=%d mesh=%dx%d ..."
            % (head["winner"], n, batch, nb, nt))
    import numpy as np

    import dpf_tpu
    from dpf_tpu.parallel.sharded import ShardedDPFServer
    from dpf_tpu.utils.config import EvalConfig
    dpf = dpf_tpu.DPF(config=EvalConfig(
        prf_method=prf, scheme=win_c["scheme"], radix=win_c["radix"]))
    table = np.random.default_rng(n ^ 0x3a7).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    srv = ShardedDPFServer(
        table, make_mesh(n_table=nt, n_batch=nb,
                         devices=devices[:n_devices]),
        prf_method=prf, batch_size=batch, radix=win_c["radix"],
        scheme=win_c["scheme"])
    serve_rec = tune_mesh_serving(srv, dpf, cap=batch, reps=reps,
                                  cache=cache, force=force, log=log)
    sm = serve_rec["measured"]
    total_rejected += sm["rejected"]

    record = {
        "metric": "mesh-path autotune matrix: %d constructions x %d "
                  "mesh splits x %d shapes, tuned vs mesh heuristic, "
                  "every timed candidate equality-gated against the "
                  "scalar oracle" % (len(CONSTRUCTIONS), len(splits),
                                     len(shapes)),
        "n_devices": n_devices,
        "forced_cpu_mesh": not native,
        "fingerprint": device_fingerprint(),
        "prf": PRF_NAMES[prf],
        "points": points,
        "serve": {
            "construction": head["winner"],
            "mesh": sm["mesh"], "cap": sm["cap"],
            "tuned_knobs": serve_rec["knobs"],
            "qps": sm["qps"], "elapsed_s": sm["elapsed_s"],
            "candidates_tried": sm["candidates_tried"],
            "rejected": sm["rejected"],
            "from_cache": not serve_rec["searched"],
        },
        "total_rejected": total_rejected,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
        "tuning_cache": cache.path,
        "compilation_cache": compcache.enabled_dir(),
        "cache_counters": CACHE_COUNTERS.as_dict(),
        "checked": True,  # gate-first: no candidate timed un-verified
    }
    if not quiet:
        print(json.dumps(record), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8,
                    help="forced virtual CPU device count (default 8)")
    ap.add_argument("--native", action="store_true",
                    help="use the real device mesh (the relay TPU "
                         "record) instead of forcing a CPU mesh")
    ap.add_argument("--shapes", default=None,
                    help="comma list of N:B points (default %s)"
                         % ",".join("%d:%d" % s for s in DEFAULT_SHAPES))
    ap.add_argument("--prf", type=int, default=1,
                    help="PRF id (default 1=Salsa20; 0=DUMMY, "
                         "3=AES128)")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--force", action="store_true",
                    help="re-measure even with a warm tuning cache")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    shapes = DEFAULT_SHAPES
    if args.shapes:
        shapes = tuple(tuple(int(x) for x in p.split(":"))
                       for p in args.shapes.split(","))
    return multichip_bench(shapes, n_devices=args.devices,
                           native=args.native, prf=args.prf,
                           reps=args.reps, force=args.force,
                           out=args.out)


if __name__ == "__main__":
    main()
