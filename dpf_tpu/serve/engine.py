"""Pipelined serving engine: keep the device saturated under a stream of
query batches.

The blocking loop (``DPF.eval_tpu`` per batch) serializes host and
device: deserialize keys, pack, dispatch, then ``np.asarray`` — the
device idles while the host parses the next batch (the host/device
overlap problem of the TPU linear-algebra literature, PAPERS.md
arXiv:2112.09017).  The engine splits that pipeline:

* **Vectorized ingest** — a whole batch decodes through the batched wire
  codec (``keygen.decode_keys_batched`` / ``radix4``'s counterpart) in
  O(1) Python ops instead of a per-key loop.
* **Double-buffered dispatch** — ``submit()`` returns a future
  immediately after enqueueing the jitted program (JAX async dispatch,
  no premature ``np.asarray``); the host packs batch k+1 while batch k
  runs on device.  A configurable ``max_in_flight`` window bounds the
  queue: when full, ``submit`` blocks on the oldest outstanding dispatch
  (backpressure) before enqueueing more.
* **Shape-bucketed batching** — ragged batch sizes pad up to a small
  fixed set of power-of-two buckets (``serve/buckets.py``) so at most
  ``len(buckets)`` XLA programs compile; ``warmup()`` precompiles all of
  them at init.

The engine is server-agnostic: any object with ``_decode_batch(keys) ->
packed batch`` and ``_dispatch_packed(pk) -> device array`` works — both
``api.DPF`` (single chip, all three constructions: binary GGM, radix-4,
and sqrt-N via ``sqrtn.PackedSqrtKeys``) and
``parallel.sharded.ShardedDPFServer`` (mesh path) provide the pair.
Results are bit-identical to the blocking loop (pad rows are discarded;
per-key math is batch-shape independent).
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from ..core.expand import DeadlineExceeded
from ..obs.flight import FLIGHT
from ..obs.tracer import span
from ..utils.profiling import EngineCounters, note_swallowed
from .buckets import Buckets


class LoadShed(RuntimeError):
    """Admission control rejected a batch instead of queueing it.

    Raised by ``ServingEngine.submit`` when ``shed=True`` and either the
    pending-future queue is at ``max_queue_depth`` or the engine's p99
    latency estimate exceeds ``slo_s`` while a backlog exists.  The
    batch was NOT dispatched — nothing to unwind; the caller (a router,
    a front-end) answers the client with a retry/reject instead of
    letting the queue grow past the SLO."""


class EngineClosed(RuntimeError):
    """The engine was decommissioned (``ServingEngine.close``): every
    subsequent ``submit`` is rejected cleanly.  Distinct from
    ``LoadShed`` (an admission *decision* that self-heals) — a closed
    engine never comes back; the caller must route elsewhere.  This is
    the autoscaler's scale-down contract (``plan/autoscale.py``): a
    retained handle that submits after the drain gets this instead of
    racing the teardown."""


class _Part:
    """One dispatched (bucket-padded) chunk of a submitted batch."""
    __slots__ = ("dev", "n_real", "bucket", "out")

    def __init__(self, dev, n_real, bucket):
        self.dev = dev          # device array, possibly still in flight
        self.n_real = n_real    # rows that are real queries (not pad)
        self.bucket = bucket    # padded dispatch size (fault targeting)
        self.out = None         # resolved host array


class EngineFuture:
    """Result handle for one submitted batch.

    ``result()`` blocks until this batch — and, FIFO, every batch
    submitted before it — has left the device, then returns the
    ``[batch, entry_size]`` int32 share array.
    """
    __slots__ = ("_engine", "_parts", "_value", "_t0")

    def __init__(self, engine):
        self._engine = engine
        self._parts = []
        self._value = None
        self._t0 = None     # submit-entry perf_counter (latency ring)

    def done(self) -> bool:
        return self._value is not None

    def result(self):
        if self._value is None:
            self._engine._resolve_through(self)
        return self._value


class ServingEngine:
    """Throughput-oriented DPF serving over one prepared table.

    Args:
      server: an ``api.DPF`` after ``eval_init`` or a
        ``parallel.sharded.ShardedDPFServer``.
      max_in_flight: dispatch-window size (outstanding device programs
        before ``submit`` applies backpressure).  2 is classic double
        buffering.
      buckets: a ``Buckets``, an iterable of power-of-two sizes, or None
        for the default /2 ladder under the server's batch cap.  On the
        mesh path, sizes should be multiples of the mesh "batch" axis or
        the dispatch pads further (still one program per bucket).
      warmup: precompile every bucket at construction.
      max_queue_depth: admission bound on PENDING futures (batches
        submitted but not yet resolved).  When reached, ``submit``
        resolves the oldest future first (deeper backpressure than the
        dispatch window) — or, with ``shed=True``, rejects the batch.
      slo_s: target per-batch latency.  With ``shed=True``, a batch
        arriving while the p99 of the latency ring exceeds ``slo_s``
        AND a backlog exists is rejected (``LoadShed``) rather than
        queued — an idle engine always admits, so shedding self-heals
        once the backlog drains.
      shed: reject (raise ``LoadShed``, counted in
        ``stats.shed_batches/shed_queries``) instead of blocking when
        admission control trips.
      label: construction label for fault targeting and router
        bookkeeping (``serve/faults.py``); None outside a router.
      injector: a ``faults.FaultInjector`` consulted at the first-class
        injection points (before each dispatch, on each resolved
        result, before each warmup precompile).  None = no injection —
        the points cost one attribute check on the hot path.

    ``deadline`` (a ``time.monotonic()`` value — immune to NTP steps;
    pass ``timeout_s`` to have the engine compute it) is checked
    cooperatively between dispatches and resolutions — never mid-compile
    (relay safety, docs/STATUS.md) — raising ``expand.DeadlineExceeded``
    and counting the trip in ``stats.deadline_misses``.
    """

    def __init__(self, server, *, max_in_flight: int = 2, buckets=None,
                 warmup: bool = False, deadline: float | None = None,
                 timeout_s: float | None = None,
                 max_queue_depth: int | None = None,
                 slo_s: float | None = None, shed: bool = False,
                 label: str | None = None, injector=None,
                 tenant: str | None = None):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1 (got %d)"
                             % max_in_flight)
        if deadline is not None and timeout_s is not None:
            raise ValueError(
                "pass deadline (absolute time.monotonic()) or timeout_s "
                "(relative), not both")
        if timeout_s is not None:
            deadline = time.monotonic() + timeout_s
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 (got %d)"
                             % max_queue_depth)
        n = getattr(server, "table_num_entries", None)
        if n is None:
            n = getattr(server, "n", None)
        if n is None:
            raise RuntimeError(
                "server has no initialized table — call eval_init first")
        self._server = server
        self._n = int(n)
        self._out_width = getattr(server, "table_effective_entry_size",
                                  None) or getattr(server, "entry_size")
        self.max_in_flight = int(max_in_flight)
        if not isinstance(buckets, Buckets):
            cap = (getattr(server, "BATCH_SIZE", None)
                   or getattr(server, "batch_size", 512))
            buckets = Buckets(buckets if buckets is not None
                              else Buckets.default_sizes(cap))
        self.buckets = buckets
        self.deadline = deadline
        self.max_queue_depth = max_queue_depth
        self.slo_s = slo_s
        self.shed = bool(shed)
        self.label = label
        self.tenant = tenant      # owning tenant (metrics/flight labels)
        self._injector = injector
        self.stats = EngineCounters()
        self._closed = False      # set by close(); submit rejects after
        self._queue = deque()     # _Part refs, dispatch order, unresolved
        self._pending = deque()   # futures with unresolved parts, FIFO
        # Persistent XLA compilation cache, on by default for the serve
        # path (disable: DPF_TPU_COMPILE_CACHE=0): warmup is real
        # serving latency, and a warm cache turns each bucket's compile
        # into a deserialize on every process after the first.
        try:
            from ..tune import compcache
            compcache.enable()
        except Exception as e:  # cache must never break serving —
            # but the cause stays diagnosable (counter + one-shot warn)
            note_swallowed("serve.engine.compcache_enable", e, self.stats)
        try:
            from ..obs.metrics import register_engine
            register_engine(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.engine.register_metrics", e, self.stats)
        if warmup:
            self.warmup()

    # ------------------------------------------------------------- submit

    def submit(self, keys) -> EngineFuture:
        """Decode + dispatch one batch; returns a future immediately.

        The host-side work here is the vectorized decode and the bucket
        pad; the device program is enqueued asynchronously.  When the
        in-flight window is full, blocks on the oldest outstanding
        dispatch first (backpressure).  Admission control
        (``max_queue_depth``/``slo_s``) runs first: over the bound the
        batch either waits on the oldest pending future or — with
        ``shed=True`` — is rejected with ``LoadShed`` before any decode
        or dispatch work happens.
        """
        if self._closed:
            raise EngineClosed(
                "engine %r is closed — submit after close()"
                % (self.label or "engine",))
        self._check_deadline()
        t_enter = time.perf_counter()
        # pre-decoded packed batches (LookupStream) carry .batch
        b_req = getattr(keys, "batch", None) or len(keys)
        with span("submit", engine=self.label or "engine", batch=b_req):
            with span("admit"):
                self._admit(b_req)
            t0 = time.perf_counter()
            with span("pack", phase="decode"):
                pk = self._server._decode_batch(keys)
            b = pk.batch
            fut = EngineFuture(self)
            # the latency ring measures from submit ENTRY: a blocking
            # admission wait is exactly the client-observed queueing the
            # p99 SLO trigger exists to see (pack_time_s stays post-admit)
            fut._t0 = t_enter
            try:
                for lo, hi in self.buckets.chunks(b):
                    self._check_deadline()
                    size = self.buckets.bucket_for(hi - lo)
                    with span("pack", phase="pad", bucket=size):
                        padded = pk.slice(lo, hi).pad_to(size)
                    self.stats.pack_time_s += time.perf_counter() - t0
                    while len(self._queue) >= self.max_in_flight:
                        self._check_deadline()
                        self._resolve_one()
                    with span("dispatch", bucket=size):
                        if self._injector is not None:
                            # first-class injection point: may sleep
                            # (straggler), raise InjectedDispatchError, or
                            # raise EngineDead — the partial-unwind below
                            # handles either
                            self._injector.on_dispatch(self, size)
                        t1 = time.perf_counter()
                        dev = self._server._dispatch_packed(padded)
                        self.stats.dispatch_time_s += (time.perf_counter()
                                                       - t1)
                    part = _Part(dev, hi - lo, size)
                    fut._parts.append(part)
                    self._queue.append(part)
                    self.stats.note_dispatch(padded=size - (hi - lo),
                                             in_flight=len(self._queue))
                    t0 = time.perf_counter()
            except BaseException:
                # Unwind a partially submitted batch: its dispatched parts
                # must not stay orphaned in the window (the future is never
                # returned), so block on each (never interrupt an in-flight
                # program — relay safety) and drop it from the queue.
                for p in fut._parts:
                    try:
                        self._queue.remove(p)
                    except ValueError:
                        pass
                    if p.dev is not None:
                        np.asarray(p.dev)
                        p.dev = None
                raise
            self.stats.batches_submitted += 1
            self.stats.queries_submitted += b
            self._pending.append(fut)
            return fut

    # ---------------------------------------------------------- resolution

    def _resolve_one(self):
        """Block on the oldest in-flight dispatch and store its rows."""
        part = self._queue.popleft()
        with span("wait", bucket=part.bucket):
            t0 = time.perf_counter()
            part.out = np.asarray(part.dev)[:part.n_real]
            if self._injector is not None:
                # injection point: corrupted-share faults replace the rows
                # here, downstream of the device — the bit-gating oracle
                # path must catch every one (integrity-check role)
                part.out = self._injector.on_result(self, part.bucket,
                                                    part.out)
            self.stats.wait_time_s += time.perf_counter() - t0
            part.dev = None

    def _finalize(self, fut: EngineFuture):
        with span("decode", parts=len(fut._parts)):
            parts = fut._parts
            if len(parts) == 1:
                out = parts[0].out
            else:
                out = np.concatenate([p.out for p in parts])
            fut._value = np.ascontiguousarray(out[:, :self._out_width])
            fut._parts = []
            if fut._t0 is not None:
                self.stats.note_latency(time.perf_counter() - fut._t0)

    def _resolve_through(self, fut: EngineFuture):
        """Resolve futures FIFO until (and including) ``fut``."""
        while self._pending:
            head = self._pending.popleft()
            while any(p.out is None for p in head._parts):
                self._resolve_one()
            self._finalize(head)
            if head is fut:
                return
        if fut._value is None:  # not one of ours
            raise RuntimeError("future does not belong to this engine")

    def drain(self) -> None:
        """Resolve every outstanding dispatch (blocks until the device is
        idle); all previously returned futures become ``done()``."""
        while self._pending:
            self._check_deadline()
            head = self._pending.popleft()
            while any(p.out is None for p in head._parts):
                self._resolve_one()
            self._finalize(head)

    def close(self) -> None:
        """Decommission: drain every outstanding dispatch, then reject
        all future ``submit``s with ``EngineClosed``.  In-flight work
        completes (every previously returned future resolves normally);
        counters are left intact for the caller's final accounting.
        Idempotent — the autoscaler's scale-down path
        (``plan/autoscale.ReplicaPool.scale_down``) drains explicitly
        first and then calls this for the rejection contract."""
        self.drain()
        if not self._closed:
            self._closed = True
            ev = dict(engine=self.label or "engine",
                      served=self.stats.queries_submitted)
            if self.tenant is not None:
                ev["tenant"] = self.tenant
            FLIGHT.record("engine_close", **ev)

    @property
    def closed(self) -> bool:
        return self._closed

    # ------------------------------------------------------------- warmup

    def warmup(self, tune: bool = False, trace=None) -> None:
        """Precompile every bucket's program with synthetic keys.

        A zero-codeword key with a valid header (depth/n — or, for
        scheme='sqrtn', the default K x R split) decodes into the exact
        array shapes real traffic produces, so each dispatch here
        populates the jit cache for one bucket size; outputs are
        discarded and none of the serving counters move.  (Sqrt-N keys
        minted with a custom ``n_keys`` split compile their own program
        on first dispatch — only the default split is prewarmed.)
        Because each dispatch goes through ``resolved_eval_knobs``, the
        precompiled program is whatever kernel the resolver picks —
        for sqrtn that includes ``kernel_impl`` ("xla" scan or the
        fused "pallas" grid kernel) AND any searched kernel variant
        (a ``kvariant`` tuning-cache entry from ``tune/
        kernel_search.py`` resolves with ``kernel_resolved_from=
        "searched"`` and its structural keywords thread through to the
        launcher), so real traffic hits a warm cache for the same
        program the search picked.

        ``tune=True`` first re-tunes the serving knobs in place: the
        persistent tuning cache (``tune/cache.py``) is consulted for
        this (device, table shape, cap) and, on a miss, the grid search
        (``tune.serve_tune.tune_serving``) runs against a synthetic
        arrival trace (or ``trace``, a list of batch sizes) — the
        engine's ``buckets`` and ``max_in_flight`` are then replaced by
        the measured winner before the precompile loop runs.  Searching
        needs a server that can mint keys (``api.DPF``); on the mesh
        path a cache miss leaves the knobs untouched.
        """
        if tune:
            from ..tune.serve_tune import lookup_serve_knobs, tune_serving
            cap = self.buckets.max
            knobs = lookup_serve_knobs(self._server, cap)
            if knobs is None and hasattr(self._server, "gen"):
                knobs = tune_serving(self._server, cap=cap,
                                     trace=trace)["knobs"]
            if knobs:
                self.buckets = Buckets(knobs["buckets"])
                self.max_in_flight = int(knobs["max_in_flight"])
        for size in self.buckets.sizes:
            if self._injector is not None:
                # injection point: compile failures fire here (and a
                # dead engine's warmup stays dead) — a supervisor
                # rebuild's re-warm exercises exactly this path
                self._injector.on_warmup(self, size)
            np.asarray(self._server._dispatch_packed(
                self._synthetic_packed(size)))

    def _synthetic_packed(self, size: int):
        """A zero-codeword packed batch with the exact array shapes real
        traffic produces at this bucket size (warmup/probe input)."""
        from ..core.keygen import PackedKeys
        if getattr(self._server, "scheme", "logn") == "sqrtn":
            from ..core import sqrtn
            from ..core.sqrtn import PackedSqrtKeys
            k, r = sqrtn.default_split(self._n)
            return PackedSqrtKeys(
                seeds=np.zeros((size, k, 4), dtype=np.uint32),
                cw1=np.zeros((size, r, 4), dtype=np.uint32),
                cw2=np.zeros((size, r, 4), dtype=np.uint32),
                n=self._n)
        return PackedKeys(
            cw1=np.zeros((size, 64, 4), dtype=np.uint32),
            cw2=np.zeros((size, 64, 4), dtype=np.uint32),
            last=np.zeros((size, 4), dtype=np.uint32),
            depth=self._n.bit_length() - 1, n=self._n)

    def probe(self, reps: int = 1) -> dict:
        """Measure one warmed dispatch per bucket size (seconds).

        The router's cost-model seed (serve/router.py): each bucket's
        program runs once untimed (compile/warm — a no-op when
        ``warmup()`` already ran and the jit cache is hot), then
        best-of-``reps`` timed blocking dispatches.  Synthetic
        zero-codeword keys measure the same program real traffic runs
        (the eval is data-independent).  Serving counters do not move.
        Returns ``{bucket_size: seconds}``.
        """
        out = {}
        for size in self.buckets.sizes:
            if self._injector is not None:
                # a dead engine must fail its probe: the breaker's
                # half-open re-probe relies on this to stay open until
                # the supervisor's rebuilt engine is actually serving
                self._injector.on_warmup(self, size)
            pk = self._synthetic_packed(size)
            np.asarray(self._server._dispatch_packed(pk))
            best = float("inf")
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                np.asarray(self._server._dispatch_packed(pk))
                best = min(best, time.perf_counter() - t0)
            out[size] = best
        return out

    # ------------------------------------------------------------ plumbing

    def resolved_config(self) -> dict:
        """The engine's effective program-shape config — bucket ladder,
        in-flight window, and (when the server exposes its resolution,
        ``DPF.resolved_eval_knobs``) the eval knobs of the cap-size
        program.  Benchmark records embed this so every BENCH_* file is
        self-describing about what actually ran."""
        d = {"buckets": list(self.buckets.sizes),
             "max_in_flight": self.max_in_flight}
        rk = getattr(self._server, "resolved_eval_knobs", None)
        if callable(rk):
            try:
                d.update(rk(self.buckets.max))
            except Exception as e:  # diagnostics must never break
                # serving — but the cause stays diagnosable
                note_swallowed("serve.engine.resolved_config", e,
                               self.stats)
        return d

    def _check_deadline(self):
        # monotonic, not wall-clock: an NTP step must neither fire the
        # deadline spuriously nor starve it forever
        if self.deadline is not None and time.monotonic() > self.deadline:
            self.stats.deadline_misses += 1
            ev = dict(engine=self.label or "engine",
                      pending=len(self._pending),
                      in_flight=len(self._queue))
            if self.tenant is not None:
                ev["tenant"] = self.tenant
            FLIGHT.record("deadline", **ev)
            raise DeadlineExceeded(
                "serving-engine deadline passed between dispatches")

    def _admit(self, n_queries: int):
        """Admission control, before any decode/dispatch work.

        Two triggers: the pending-future queue at ``max_queue_depth``,
        or (``slo_s`` set) the ring's p99 latency estimate over the SLO
        while a backlog exists.  ``shed=True`` rejects (``LoadShed``);
        otherwise the engine blocks on the oldest pending future until
        the queue is back under the bound (the p99 trigger never
        blocks — waiting would only worsen the latency it guards).
        """
        over_depth = (self.max_queue_depth is not None
                      and len(self._pending) >= self.max_queue_depth)
        over_slo = False
        if self.slo_s is not None and (self._pending or self._queue):
            p99 = self.stats.p99
            over_slo = p99 is not None and p99 > self.slo_s
        if self.shed and (over_depth or over_slo):
            self.stats.shed_batches += 1
            self.stats.shed_queries += n_queries
            ev = dict(engine=self.label or "engine", batch=n_queries,
                      reason=("queue_depth" if over_depth
                              else "p99_over_slo"),
                      pending=len(self._pending),
                      p99=self.stats.p99, slo_s=self.slo_s)
            if self.tenant is not None:
                ev["tenant"] = self.tenant
            FLIGHT.record("shed", **ev)
            raise LoadShed(
                "admission control rejected the batch (%s; pending=%d, "
                "p99=%s, slo_s=%s)"
                % ("queue depth" if over_depth else "p99 over SLO",
                   len(self._pending), self.stats.p99, self.slo_s))
        while (self.max_queue_depth is not None
               and len(self._pending) >= self.max_queue_depth):
            self._check_deadline()
            self._resolve_through(self._pending[0])

    @property
    def in_flight(self) -> int:
        return len(self._queue)

    def __repr__(self):
        return ("ServingEngine(n=%d, buckets=%s, max_in_flight=%d, "
                "served=%d)" % (self._n, list(self.buckets.sizes),
                                self.max_in_flight,
                                self.stats.queries_submitted))
