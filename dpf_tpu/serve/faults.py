"""Seeded fault injection + the recovery machinery it exercises.

The serving stack's redundancy substrate is the multi-construction
router (serve/router.py): three independent ways to answer the same
query over the same table mean a failing construction is a *routing*
problem, not a new code path (the Chameleon scheme-switching move,
PAPERS.md arXiv:2410.05934, read as a failover mechanism).  This module
supplies both sides of the failure story:

**Injection** — ``FaultPlan`` (a list of ``FaultSpec``) + seed compiles
into a ``FaultInjector`` consulted at first-class injection points in
``ServingEngine.submit``/``_resolve_one``/``warmup`` and (via the
engines) ``SchemeRouter.submit``.  Six fault kinds, each targetable by
construction x bucket x arrival-index window with a per-consult
probability:

* ``dispatch_error``  — the dispatch raises (a flaky device/runtime),
* ``compile_error``   — warmup/rebuild precompile raises,
* ``latency``         — a straggler: the dispatch sleeps ``latency_s``,
* ``corrupt_shares``  — the resolved result rows are bit-flipped (the
  existing bit-gating oracle path must catch every one — the gate
  doubles as an integrity check),
* ``engine_death``    — the CURRENT engine object is poisoned: every
  subsequent dispatch/warmup on it raises ``EngineDead`` until the
  supervisor rebuilds a fresh engine over the same prepared server,
* ``host_drop``       — a whole serving HOST dies (the cluster tier's
  fault, ``parallel/cluster.py``): the targeted host's engine is
  poisoned like ``engine_death`` but raises ``HostDropped`` and its
  heartbeats (``on_heartbeat``) fail too, so liveness sweeps detect the
  loss even between dispatches.  Target by ``construction`` = the host
  label ("host0", ...).

Decisions are **deterministic under the plan seed**: each consult draws
from ``np.random.default_rng((seed, spec_index, arrival, consult))``,
a pure function of the targeting coordinates — the same plan replayed
over the same trace injects the identical faults (per-spec consult
order; single-threaded replay is exactly reproducible).

**Recovery** — ``RetryPolicy`` (bounded attempts, exponential backoff
with seeded jitter; ``submit_with_retry`` applies it at batch
granularity, reusing ``ServingEngine.submit``'s partial-unwind so a
retried engine is always consistent), ``CircuitBreaker`` (K consecutive
failures -> open; half-open re-probe after ``reset_s``), and
``EngineSupervisor`` (rebuilds a dead engine over the same prepared
server and re-warms it, in the background by default, while the router
serves degraded).  The router wires them together; the chaos bench
(``serve/bench_chaos.py``, ``benchmark.py --chaos``) replays escalating
plans and commits the availability record.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from ..core.expand import DeadlineExceeded
from ..obs.flight import FLIGHT
from ..obs.tracer import span
from .engine import LoadShed, ServingEngine

#: fault kinds a FaultSpec can name
KINDS = ("dispatch_error", "compile_error", "latency", "corrupt_shares",
         "engine_death", "host_drop")


class FaultError(RuntimeError):
    """Base class of every injected fault (so harnesses can tell an
    injected failure from a genuine one)."""


class InjectedDispatchError(FaultError):
    """An injected per-dispatch failure (``kind="dispatch_error"``)."""


class InjectedCompileError(FaultError):
    """An injected warmup/precompile failure (``kind="compile_error"``)."""


class EngineDead(FaultError):
    """The engine object is poisoned (``kind="engine_death"``): every
    dispatch raises until the supervisor rebuilds a fresh engine."""


class HostDropped(EngineDead):
    """A whole serving host died (``kind="host_drop"``): every engine on
    it is gone at once and its heartbeats stop.  Subclasses
    ``EngineDead`` so engine-level recovery (router exclusion,
    supervisor notify) applies unchanged; the cluster tier
    (``parallel/cluster.py``) additionally takes the host out of the
    scatter plan and re-shards or degrades."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One targeted fault stream.

    ``construction``/``bucket`` of None match anything; ``start``/
    ``stop`` bound the arrival-index window (stop exclusive, None =
    open-ended); ``p`` is the per-consult firing probability;
    ``max_fires`` bounds total fires (``engine_death`` is implicitly
    once).  ``latency_s`` only applies to ``kind="latency"``."""
    kind: str
    construction: str | None = None
    bucket: int | None = None
    start: int = 0
    stop: int | None = None
    p: float = 1.0
    latency_s: float = 0.05
    max_fires: int | None = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError("unknown fault kind %r (one of %s)"
                             % (self.kind, ", ".join(KINDS)))
        if not 0.0 <= self.p <= 1.0:
            raise ValueError("p must be in [0, 1] (got %r)" % (self.p,))

    def matches(self, label: str | None, bucket: int | None,
                arrival: int) -> bool:
        if self.construction is not None and label != self.construction:
            return False
        if self.bucket is not None and bucket != self.bucket:
            return False
        if arrival < self.start:
            return False
        return self.stop is None or arrival < self.stop

    def as_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        """Inverse of ``as_dict`` (``as_dict`` drops None fields, so a
        round-trip restores the dataclass defaults for them)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


class FaultPlan:
    """An immutable list of ``FaultSpec`` plus the seed that makes every
    injection decision reproducible.  ``injector()`` mints the runtime
    object the engines consult.  ``as_dict``/``from_dict`` round-trip
    the full plan, so a committed chaos-style bench record (which embeds
    ``record["faults"]["plan"]``) names an exactly replayable fault
    sequence."""

    def __init__(self, specs=(), seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)

    def injector(self) -> "FaultInjector":
        return FaultInjector(self)

    def as_dict(self) -> dict:
        return {"seed": self.seed,
                "specs": [s.as_dict() for s in self.specs]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(specs=[FaultSpec.from_dict(s)
                          for s in d.get("specs", ())],
                   seed=d.get("seed", 0))


class FaultInjector:
    """Runtime fault oracle, consulted at the engine injection points.

    The harness calls ``begin_arrival(j)`` before each arrival's
    submit; every consult then decides by a seeded hash of
    (spec, arrival, consult-count) — deterministic, order-independent
    across specs, replayable.  ``injected`` counts fires per kind;
    ``corruptions`` lists (construction, arrival) per corrupted batch
    so the bench can prove 0 bit-gate escapes.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.arrival = -1             # -1 = outside any arrival (warmup)
        self.tenant = None            # set by TenantRouter (flight label)
        self.injected = {k: 0 for k in KINDS}
        self.corruptions = []         # (construction, arrival)
        self._consults = {}           # (spec_idx, arrival) -> count
        self._fires = {}              # spec_idx -> total fires
        self._dead = set()            # id(engine) of poisoned engines
        self._lock = threading.Lock()

    def begin_arrival(self, j: int) -> None:
        self.arrival = int(j)

    # ------------------------------------------------------ decisions

    def _fires_left(self, idx: int, spec: FaultSpec) -> bool:
        # death faults poison persistent state — once is the event
        cap = (1 if spec.kind in ("engine_death", "host_drop")
               else spec.max_fires)
        return cap is None or self._fires.get(idx, 0) < cap

    def _decide(self, idx: int, spec: FaultSpec) -> bool:
        """One deterministic draw for (spec, current arrival, consult
        count).  Repeated consults at the same arrival (multi-chunk
        batches, retries) draw independently, so a retry CAN succeed
        against a probabilistic fault."""
        key = (idx, self.arrival)
        with self._lock:
            consult = self._consults.get(key, 0)
            self._consults[key] = consult + 1
        if spec.p >= 1.0:
            fired = True
        else:
            rng = np.random.default_rng(
                (self.plan.seed, idx, self.arrival + 1, consult))
            fired = bool(rng.random() < spec.p)
        if fired:
            with self._lock:
                if not self._fires_left(idx, spec):
                    return False
                self._fires[idx] = self._fires.get(idx, 0) + 1
                self.injected[spec.kind] += 1
        return fired

    def _firing(self, kinds, label, bucket):
        for idx, spec in enumerate(self.plan.specs):
            if (spec.kind in kinds and self._fires_left(idx, spec)
                    and spec.matches(label, bucket, self.arrival)
                    and self._decide(idx, spec)):
                # flight-record every fire with the SAME arrival index
                # the route decision carries — the join key that
                # attributes a fault to the decision that placed it
                ev = dict(fault=spec.kind, construction=label,
                          bucket=bucket, arrival=self.arrival)
                if self.tenant is not None:
                    ev["tenant"] = self.tenant
                FLIGHT.record("fault", **ev)
                yield spec

    # ----------------------------------------------- injection points

    def on_dispatch(self, engine, bucket: int) -> None:
        """Consulted by ``ServingEngine.submit`` immediately before each
        chunk's device dispatch.  May sleep (latency), poison the engine
        (engine_death -> ``EngineDead``), or raise
        ``InjectedDispatchError``; the engine's existing partial-unwind
        handles either exception."""
        label = getattr(engine, "label", None)
        if id(engine) in self._dead:
            raise EngineDead("engine %r is dead (injected)" % (label,))
        for spec in self._firing(("engine_death", "host_drop"), label,
                                 bucket):
            self._dead.add(id(engine))
            if spec.kind == "host_drop":
                raise HostDropped(
                    "host %r dropped at arrival %d (injected)"
                    % (label, self.arrival))
            raise EngineDead("engine %r killed at arrival %d (injected)"
                             % (label, self.arrival))
        for spec in self._firing(("latency",), label, bucket):
            time.sleep(spec.latency_s)
        for _ in self._firing(("dispatch_error",), label, bucket):
            raise InjectedDispatchError(
                "dispatch failed at arrival %d on %r (injected)"
                % (self.arrival, label))

    def on_result(self, engine, bucket: int, out):
        """Consulted by ``ServingEngine._resolve_one`` on the resolved
        host rows: a firing corrupt spec returns a bit-flipped COPY (the
        XOR keeps the corruption silent-looking — right shape/dtype,
        wrong value — exactly what the bit gate must catch)."""
        label = getattr(engine, "label", None)
        for _ in self._firing(("corrupt_shares",), label, bucket):
            bad = np.array(out, copy=True)
            if bad.size:
                bad.flat[0] ^= np.int32(1 << 7)
            self.corruptions.append((label, self.arrival))
            return bad
        return out

    def on_warmup(self, engine, bucket: int) -> None:
        """Consulted before each warmup/probe precompile dispatch: a
        dead engine stays dead, and compile_error specs fire here."""
        label = getattr(engine, "label", None)
        if id(engine) in self._dead:
            raise EngineDead("engine %r is dead (injected)" % (label,))
        for _ in self._firing(("compile_error",), label, bucket):
            raise InjectedCompileError(
                "precompile failed for %r bucket %d (injected)"
                % (label, bucket))

    def on_heartbeat(self, engine) -> None:
        """Consulted by the cluster tier's liveness sweep
        (``ClusterRouter.check_hosts``): a ``host_drop`` spec fires here
        too — with ``bucket=None`` targeting, heartbeats and dispatches
        share the spec — and an already-dropped host's heartbeat keeps
        failing, so host loss is detectable between dispatches."""
        label = getattr(engine, "label", None)
        if id(engine) in self._dead:
            raise HostDropped("host %r is down (injected)" % (label,))
        for _ in self._firing(("host_drop",), label, None):
            self._dead.add(id(engine))
            raise HostDropped(
                "host %r dropped at arrival %d (injected, heartbeat)"
                % (label, self.arrival))

    def is_dead(self, engine) -> bool:
        return id(engine) in self._dead

    def stats(self) -> dict:
        return {"injected": dict(self.injected),
                "corrupted_batches": len(self.corruptions)}


# --------------------------------------------------------------- retry

@dataclasses.dataclass
class RetryPolicy:
    """Bounded retries with exponential backoff and seeded jitter.

    ``max_attempts`` counts the first try; backoff before attempt k+1
    is ``backoff_s * backoff_mult**(k-1) * (1 + jitter * u)`` with u
    drawn from a seeded rng (deterministic sleep schedule under the
    seed).  ``LoadShed`` and ``DeadlineExceeded`` are never retryable:
    admission control and deadlines are *decisions*, not faults —
    retrying them would defeat the mechanisms (and double-count sheds).
    """
    max_attempts: int = 3
    backoff_s: float = 0.005
    backoff_mult: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1 (got %d)"
                             % self.max_attempts)
        self._rng = np.random.default_rng(self.seed)

    def retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, (LoadShed, DeadlineExceeded)):
            return False
        return isinstance(exc, Exception)

    def backoff(self, attempt: int) -> float:
        """Backoff (seconds) after failed attempt ``attempt`` (1-based)."""
        base = self.backoff_s * self.backoff_mult ** max(0, attempt - 1)
        return base * (1.0 + self.jitter * float(self._rng.random()))

    def sleep(self, attempt: int) -> None:
        dt = self.backoff(attempt)
        if dt > 0:
            time.sleep(dt)


def submit_with_retry(submit, policy: RetryPolicy, stats=None):
    """Run ``submit()`` under ``policy``: on a retryable failure, back
    off and re-try (counting ``stats.retries``) up to ``max_attempts``.
    The callable must be retry-safe — ``ServingEngine.submit``'s
    partial-unwind guarantees the engine is, so wrapping it (or a
    whole-batch resubmit) directly is sound."""
    attempt = 0
    while True:
        attempt += 1
        try:
            return submit()
        except BaseException as e:
            if (not policy.retryable(e)
                    or attempt >= policy.max_attempts):
                raise
            if stats is not None:
                if hasattr(stats, "inc"):
                    stats.inc("retries")
                else:
                    stats.retries += 1
            policy.sleep(attempt)


# ------------------------------------------------------------- breaker

class CircuitBreaker:
    """Per-construction circuit breaker (serve/router.py).

    ``failures`` CONSECUTIVE failures trip closed -> open: the router
    then excludes the construction from the cost-model argmin, so its
    traffic fails over to the healthy engines over the same table.
    After ``reset_s`` the next availability check moves open ->
    half_open exactly once (``should_probe`` returns True); the router
    re-probes via the existing ``ServingEngine.probe`` and reports the
    outcome — success closes the breaker, failure re-opens it with a
    fresh timer.  Any observed SUCCESS closes the breaker from any
    state (real traffic succeeding is stronger evidence than any
    probe).  ``transitions`` records (elapsed_s, state) for the bench.
    """

    STATES = ("closed", "open", "half_open")

    def __init__(self, failures: int = 3, reset_s: float = 30.0,
                 on_open=None, name: str | None = None,
                 tenant: str | None = None):
        if failures < 1:
            raise ValueError("failures must be >= 1 (got %d)" % failures)
        self.failures = int(failures)
        self.reset_s = float(reset_s)
        self.name = name              # construction label (flight events)
        self.tenant = tenant          # owning tenant (flight events)
        self.state = "closed"
        self.consecutive = 0
        self.opened_at = None
        self.on_open = on_open        # callback(breaker) on closed->open
        self.opens = 0
        self._t0 = time.monotonic()
        self.transitions = [(0.0, "closed")]

    def _to(self, state: str) -> None:
        if state == self.state:
            return
        if state == "open":
            self.opened_at = time.monotonic()
            self.opens += 1
        prev = self.state
        self.state = state
        self.transitions.append(
            (round(time.monotonic() - self._t0, 4), state))
        ev = dict(breaker=self.name or "breaker", frm=prev, to=state,
                  consecutive_failures=self.consecutive)
        if self.tenant is not None:
            ev["tenant"] = self.tenant
        FLIGHT.record("breaker", **ev)
        if state == "open" and self.on_open is not None:
            self.on_open(self)

    def record_failure(self) -> str:
        self.consecutive += 1
        if self.state == "half_open":
            self._to("open")          # probe failed: fresh timer
        elif self.state == "closed" and self.consecutive >= self.failures:
            self._to("open")
        elif self.state == "open":
            self.opened_at = time.monotonic()   # still failing: re-arm
        return self.state

    def record_success(self) -> str:
        self.consecutive = 0
        self._to("closed")
        return self.state

    def available(self) -> bool:
        """True when routing may use this construction (closed)."""
        return self.state == "closed"

    def should_probe(self) -> bool:
        """True exactly once per open period after ``reset_s`` elapsed;
        transitions open -> half_open as a side effect."""
        if (self.state == "open" and self.opened_at is not None
                and time.monotonic() - self.opened_at >= self.reset_s):
            self._to("half_open")
            return True
        return self.state == "half_open"

    def as_dict(self) -> dict:
        return {"state": self.state, "opens": self.opens,
                "consecutive_failures": self.consecutive,
                "transitions": [list(t) for t in self.transitions]}

    def __repr__(self):
        return ("CircuitBreaker(state=%s, consecutive=%d/%d, opens=%d)"
                % (self.state, self.consecutive, self.failures,
                   self.opens))


# ---------------------------------------------------------- supervisor

class EngineSupervisor:
    """Detect-and-rebuild for a router's per-construction engines.

    ``notify(label)`` (the router calls it when a submit raises
    ``EngineDead``, or a half-open probe finds a dead engine) rebuilds
    that construction's engine over the SAME prepared server — table
    upload, tuned knobs, and bucket ladder are all reused — and
    re-warms it, by default in a background thread so the router keeps
    serving degraded on the healthy constructions meanwhile.  On
    success the new engine (old counters merged in, so history
    survives the swap) replaces the dead one and
    ``recovery.engine_restarts`` moves; the breaker stays open until
    its half-open re-probe observes the rebuilt engine working.  A
    failed rebuild (injected compile fault, dead-again engine) leaves
    the old engine in place — the next probe failure notifies again.
    """

    def __init__(self, router, background: bool = True):
        self._router = router
        self.background = bool(background)
        self._rebuilding = set()
        self._threads = []
        self._lock = threading.Lock()
        self.failed_rebuilds = 0

    def notify(self, label: str) -> bool:
        """Request a rebuild of ``label``'s engine; returns False when a
        rebuild for it is already in flight."""
        with self._lock:
            if label in self._rebuilding:
                return False
            self._rebuilding.add(label)
        if self.background:
            t = threading.Thread(target=self._rebuild, args=(label,),
                                 name="dpf-rebuild-%s" % label,
                                 daemon=True)
            self._threads.append(t)
            t.start()
        else:
            self._rebuild(label)
        return True

    def _rebuild(self, label: str) -> None:
        r = self._router
        try:
            with span("rebuild", construction=label):
                old = r.engines[label]
                fresh = ServingEngine(r.server(label), buckets=r.buckets,
                                      label=label, injector=r.injector,
                                      **r._engine_kw)
                fresh.warmup()        # re-warm BEFORE taking traffic
                fresh.stats.merge(old.stats)
                r.engines[label] = fresh
            # inc(), not +=: rebuild threads race result() callers on
            # the shared recovery counters
            r.recovery.inc("engine_restarts")
            FLIGHT.record("rebuild", construction=label, ok=True)
        except Exception as e:
            with self._lock:
                self.failed_rebuilds += 1
            FLIGHT.record("rebuild", construction=label, ok=False,
                          error=type(e).__name__)
        finally:
            with self._lock:
                self._rebuilding.discard(label)

    def join(self, timeout: float | None = None) -> None:
        """Wait for outstanding background rebuilds (bench shutdown)."""
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]

    def rebuilding(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._rebuilding))
