"""Runtime cost-model scheme router: pick the construction per batch.

The scheme-level autotuner answers "which construction is fastest for
this (N, E, B) shape" *once*, offline, at one batch size — and
``DPF(scheme="auto")`` then serves every batch with that sticky winner.
Under real traffic that choice is wrong part of the time: the fastest
construction changes with the batch size a burst actually delivers
(BENCH_SCHEME_r08.json's winners flip across (N, B) points), so a
bursty mixed-shape stream served sticky leaves qps and p99 on the
table.  ``SchemeRouter`` switches constructions at *runtime* by a live
cost model — the mid-pipeline scheme switching move of Chameleon
(PAPERS.md arXiv:2410.05934) applied to the DPF serving stack:

* One prepared server + ``ServingEngine`` per construction (binary GGM,
  radix-4, sqrt-N) over the SAME table, all sharing one bucket ladder
  so their per-bucket costs are comparable.
* A cost model ``(construction, bucket) -> EWMA seconds``, seeded from
  the tuning cache (``tune.lookup_scheme`` — the sweep's sticky winner
  and, when present, its per-construction measured seconds) and from
  startup probe dispatches (``ServingEngine.probe``), then updated
  online by the observed service time of every routed batch.
* ``route(batch)`` picks the cheapest construction for the batch's
  bucket once every enabled construction has an estimate; until then it
  falls back to the *sticky* cached winner (cold tuning cache: the
  conservative heuristic) — ``routed_from`` says which path answered,
  mirroring ``DPF.scheme_resolved_from``.

Every routed answer is a plain engine result over that construction's
keys, so it stays equality-gateable against the scalar oracle
(``DPF.eval_cpu``); the load harness (``serve/bench_load.py``) gates
every batch.  Keys are construction-specific: callers ``route`` first,
mint/fetch keys for ``decision.construction`` (``router.server(label)``
mints them), then ``submit(decision, keys)``.
"""

from __future__ import annotations

import time

from ..core.expand import DeadlineExceeded
from ..obs.flight import FLIGHT
from ..obs.tracer import span
from ..utils.profiling import EngineCounters, note_swallowed
from .buckets import Buckets
from .engine import EngineClosed, LoadShed, ServingEngine
from .faults import (CircuitBreaker, EngineDead, EngineSupervisor,
                     RetryPolicy)

#: construction labels the router can serve, in race order
LABELS = ("logn", "radix4", "sqrtn")


def build_servers(table, labels=LABELS, *, prf_method: int) -> dict:
    """One prepared ``api.DPF`` per construction label over ``table`` —
    THE construction-spelling map (label -> ctor arguments), shared by
    the router and the router tuner so they can never drift apart."""
    from ..api import DPF
    from ..utils.config import EvalConfig
    servers = {}
    for lb in labels:
        if lb == "radix4":
            srv = DPF(config=EvalConfig(prf_method=prf_method, radix=4))
        elif lb == "sqrtn":
            srv = DPF(prf=prf_method, scheme="sqrtn")
        elif lb == "logn":
            srv = DPF(prf=prf_method)
        else:
            raise ValueError("unknown construction %r (one of %s)"
                             % (lb, ", ".join(LABELS)))
        srv.eval_init(table)
        servers[lb] = srv
    return servers


def resolve_sticky(n: int, entry_size: int, prf_method: int, cap: int,
                   available=LABELS) -> tuple:
    """(construction label, resolved_from) the sticky
    ``DPF(scheme="auto")`` resolution would pin for this shape — THE
    one spelling of that rule (cache winner with nearest-batch
    fallback, else the conservative heuristic), shared by the router's
    fallback and the load benchmark's baseline so they can never
    diverge."""
    from ..tune.cache import lookup_scheme
    from ..tune.search import heuristic_scheme
    try:
        knobs = lookup_scheme(n=n, entry_size=entry_size, batch=cap,
                              prf_method=prf_method)
    except Exception as e:      # cache must never break serving
        note_swallowed("serve.router.resolve_sticky", e)
        knobs = None
    if knobs:
        win = knobs.get("construction")
        if win is None:         # pre-label records spell scheme/radix
            win = ("radix4" if knobs.get("radix") == 4
                   else knobs.get("scheme"))
        if win in available:
            return win, "cache"
    hs = heuristic_scheme(n)
    label = "radix4" if hs["radix"] == 4 else hs["scheme"]
    if label not in available:
        label = tuple(available)[0]
    return label, "heuristic"


class RouteDecision:
    """One routing answer: which construction serves this batch, and
    why (``routed_from``: "cost-model" once the model has an estimate
    for every construction at this bucket, else "cache"/"heuristic" —
    the sticky fallback's own provenance)."""
    __slots__ = ("construction", "routed_from", "bucket", "batch")

    def __init__(self, construction, routed_from, bucket, batch):
        self.construction = construction
        self.routed_from = routed_from
        self.bucket = bucket
        self.batch = batch

    def __repr__(self):
        return ("RouteDecision(%s, from=%s, bucket=%d, batch=%d)"
                % (self.construction, self.routed_from, self.bucket,
                   self.batch))


class RoutedFuture:
    """Engine future + the cost-model feedback loop: ``result()``
    resolves the underlying dispatch and folds the observed service
    time (submit→result, per dispatched chunk) back into the router's
    EWMA for (construction, bucket)."""
    __slots__ = ("_router", "_fut", "decision", "_t0", "_chunks",
                 "_observed")

    def __init__(self, router, fut, decision, t0, chunks):
        self._router = router
        self._fut = fut
        self.decision = decision
        self._t0 = t0
        self._chunks = chunks
        self._observed = False

    def done(self) -> bool:
        return self._fut.done()

    def result(self):
        try:
            out = self._fut.result()
        except (LoadShed, DeadlineExceeded, EngineClosed):
            raise               # admission decisions, not engine faults
        except Exception as e:
            self._router._note_failure(self.decision.construction, e)
            raise
        if not self._observed:
            self._observed = True
            dt = (time.perf_counter() - self._t0) / max(1, self._chunks)
            self._router._observe(self.decision.construction,
                                  self.decision.bucket, dt)
            self._router._note_success(self.decision.construction)
        return out


class SchemeRouter:
    """Serve one table through per-construction engines, routed live.

    Args:
      table: the [N, E] int32 table (uploaded once per construction —
        each has its own device layout: bit-reversed, radix-4 mixed
        order, or natural for sqrt-N).
      prf: PRF id shared by all constructions.
      constructions: subset of ``LABELS`` to race (default all three).
      cap / buckets / max_in_flight: the shared engine knobs (one
        ladder for every engine — per-bucket costs must compare).  When
        ``buckets`` is None the tuned router ladder is consulted first
        (``tune.serve_tune.lookup_router_knobs``), then the default /2
        ladder.
      ewma_alpha: weight of each new observation in the cost model.
      probe: measure one warmed dispatch per (construction, bucket) at
        startup to seed the cost model (compile cost is paid here, like
        ``warmup``).  ``probe=False`` starts cold: routing falls back
        to the sticky cached winner until observations accumulate.
      slo_s / max_queue_depth / shed: per-engine admission control
        (docs/SERVING.md "Load testing & SLOs").
      injector: optional ``faults.FaultInjector`` threaded into every
        engine (chaos testing — docs/SERVING.md "Fault tolerance").
      retry: default ``faults.RetryPolicy`` for ``submit_resilient``.
      breaker_failures / breaker_reset_s: per-construction circuit
        breaker — ``breaker_failures`` consecutive engine faults open
        it (excluded from routing); after ``breaker_reset_s`` a
        half-open re-probe (``ServingEngine.probe``) decides whether it
        re-closes.
      supervise: rebuild a dead engine over its prepared server in a
        background thread (``faults.EngineSupervisor``) while the
        router serves degraded on the remaining constructions.

    ``routed_from`` mirrors ``DPF.scheme_resolved_from``: the provenance
    of the most recent routing decision ("cost-model", "cache", or
    "heuristic"); per-decision provenance rides on ``RouteDecision``.
    """

    def __init__(self, table, *, prf=None, constructions=None,
                 cap: int | None = None, buckets=None,
                 max_in_flight: int = 2, ewma_alpha: float = 0.25,
                 warmup: bool = True, probe: bool = True,
                 probe_reps: int = 1, slo_s: float | None = None,
                 max_queue_depth: int | None = None, shed: bool = False,
                 servers: dict | None = None, injector=None,
                 retry: RetryPolicy | None = None,
                 breaker_failures: int = 5,
                 breaker_reset_s: float = 30.0,
                 supervise: bool = False,
                 tenant: str | None = None):
        from ..api import DPF
        if not 0 < ewma_alpha <= 1:
            raise ValueError("ewma_alpha must be in (0, 1] (got %r)"
                             % (ewma_alpha,))
        labels = tuple(constructions if constructions is not None
                       else (servers.keys() if servers else LABELS))
        for lb in labels:
            if lb not in LABELS:
                raise ValueError("unknown construction %r (one of %s)"
                                 % (lb, ", ".join(LABELS)))
        if not labels:
            raise ValueError("need at least one construction")
        self.constructions = labels
        self.ewma_alpha = float(ewma_alpha)
        if servers is not None:
            # prepared servers shared across routers (the tuner builds
            # its candidate routers over ONE table upload per scheme)
            missing = [lb for lb in labels if lb not in servers]
            if missing:
                raise ValueError("servers missing constructions %s"
                                 % (missing,))
            self._servers = {lb: servers[lb] for lb in labels}
            self.prf_method = self._servers[labels[0]].prf_method
        else:
            self.prf_method = DPF.DEFAULT_PRF if prf is None else prf
            self._servers = build_servers(table, labels,
                                          prf_method=self.prf_method)
        any_srv = self._servers[labels[0]]
        self.n = any_srv.table_num_entries
        self.entry_size = any_srv.table_effective_entry_size
        cap = int(cap or min(any_srv.BATCH_SIZE, 512))
        if buckets is None:
            from ..tune.serve_tune import lookup_router_knobs
            knobs = lookup_router_knobs(self, cap)
            if knobs:
                buckets = knobs["buckets"]
                max_in_flight = int(knobs["max_in_flight"])
                self.ewma_alpha = float(knobs.get("ewma_alpha",
                                                  self.ewma_alpha))
        self.buckets = (buckets if isinstance(buckets, Buckets)
                        else Buckets(buckets if buckets is not None
                                     else Buckets.default_sizes(cap)))
        self.injector = injector
        self.retry = retry
        self.tenant = tenant    # owning tenant (metrics/flight labels)
        if injector is not None and tenant is not None:
            injector.tenant = tenant
        # kept for EngineSupervisor rebuilds: a fresh engine must get
        # the SAME admission knobs (and tenant label) as the one it
        # replaces
        self._engine_kw = dict(max_in_flight=max_in_flight,
                               max_queue_depth=max_queue_depth,
                               slo_s=slo_s, shed=shed, tenant=tenant)
        self.engines = {
            lb: ServingEngine(srv, buckets=self.buckets, label=lb,
                              injector=injector, **self._engine_kw)
            for lb, srv in self._servers.items()}
        # ---- recovery machinery: per-construction breaker + counters
        self.recovery = EngineCounters()

        def _opened(_lb=None):
            # inc(), not +=: breakers trip from rebuild threads and
            # RoutedFuture.result() callers concurrently
            self.recovery.inc("breaker_opens")
        self.breakers = {
            lb: CircuitBreaker(failures=breaker_failures,
                               reset_s=breaker_reset_s,
                               on_open=_opened, name=lb, tenant=tenant)
            for lb in labels}
        self.supervisor = (EngineSupervisor(self) if supervise
                           else None)
        # ---- sticky fallback + cost-model seed from the tuning cache
        self._costs = {}            # (label, bucket) -> EWMA seconds
        self._obs_age = {}          # (label, bucket) -> routes at this
        #                             bucket since that label was last
        #                             OBSERVED (exploration clock)
        self._arrivals = {}         # bucket -> (last_t, EWMA gap s)
        self.sticky, self.sticky_resolved_from = self._resolve_sticky()
        self.routed_from = self.sticky_resolved_from
        self.route_counts = {lb: 0 for lb in labels}
        self.routed_from_counts = {}
        try:
            from ..obs.metrics import register_router
            register_router(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.router.register_metrics", e,
                           self.recovery)
        if warmup or probe:
            self.warmup(probe=probe, probe_reps=probe_reps)

    # -------------------------------------------------------- cost model

    def _resolve_sticky(self):
        """``resolve_sticky`` for this router's shape (the
        ``DPF._ensure_scheme``-equivalent winner, nearest tuned batch
        included), plus: an EXACT cap-batch scheme-sweep entry seeds
        the cost model with its per-construction measured seconds at
        the cap bucket (a measured-at-another-batch record still
        answers "which construction" but its magnitudes would mis-seed
        the EWMA)."""
        from ..tune.cache import default_cache
        from ..tune.search import scheme_cache_key
        cap = self.buckets.max
        try:
            # .lookup, not .entries.get: every cache consultation must
            # move CACHE_COUNTERS (the warm-start observability
            # contract of tune/cache.py)
            exact = default_cache().lookup(scheme_cache_key(
                n=self.n, entry_size=self.entry_size, batch=cap,
                prf_method=self.prf_method))
            if exact:
                for row in (exact.get("measured", {})
                            .get("per_construction", ())):
                    lb = row.get("construction")
                    if lb in self._servers and row.get("tuned_s"):
                        self._costs[(lb, cap)] = float(row["tuned_s"])
        except Exception as e:  # cache must never break serving
            note_swallowed("serve.router.cost_seed", e)
        return resolve_sticky(self.n, self.entry_size, self.prf_method,
                              cap, available=self.constructions)

    #: routes at a bucket before a never-re-observed construction gets
    #: one exploration dispatch: the EWMA only updates for the routed
    #: construction, so a single inflated observation (client deferred
    #: result(), a load transient) would otherwise lock a construction
    #: out of the argmin FOREVER — periodic re-measurement bounds the
    #: staleness at ~EXPLORE_EVERY batches per bucket.  256 keeps the
    #: exploration tax ~1% of routes (an explore dispatches a possibly
    #: slower construction mid-burst, which shows up directly in p99)
    #: while still re-measuring within seconds under load
    EXPLORE_EVERY = 256

    def _observe(self, label: str, bucket: int, seconds: float):
        """Fold one observed per-dispatch service time into the EWMA."""
        key = (label, bucket)
        cur = self._costs.get(key)
        self._costs[key] = (seconds if cur is None else
                            self.ewma_alpha * seconds
                            + (1 - self.ewma_alpha) * cur)
        self._obs_age[key] = 0

    def cost(self, label: str, bucket: int) -> float | None:
        """Current per-dispatch estimate (seconds), None when unknown."""
        return self._costs.get((label, bucket))

    def cost_table(self) -> dict:
        """The live EWMA cost model as a plain serializable dict:
        ``{"construction@bucket": seconds}`` — the same key spelling
        ``stats()["cost_model_ms"]`` uses (values here stay in SECONDS,
        un-rounded: this is the machine-readable export).  This is the
        digital twin's service-time input (``plan/twin.CostTable``);
        ``--load`` and ``--plan`` records embed the snapshot so every
        twin run's inputs are auditable against the router that
        produced them."""
        return {"%s@%d" % (lb, bk): s
                for (lb, bk), s in sorted(self._costs.items())}

    def seed_costs(self, table: dict) -> int:
        """Re-seed the cost model from a ``cost_table()``-shaped dict
        (string ``"label@bucket"`` or tuple ``(label, bucket)`` keys).
        Entries for constructions this router does not serve are
        skipped; returns the number of entries applied.  Seeded values
        land exactly like probe observations — the EWMA updates from
        live traffic afterwards, so a stale snapshot self-corrects at
        the same rate a poisoned probe would."""
        applied = 0
        for key, s in dict(table).items():
            if isinstance(key, str):
                if key == "overhead_s":   # twin CostTable extra field
                    continue
                lb, bk = key.rsplit("@", 1)
                key = (lb, int(bk))
            lb, bk = str(key[0]), int(key[1])
            if lb not in self.constructions:
                continue
            self._costs[(lb, bk)] = float(s)
            self._obs_age[(lb, bk)] = 0
            applied += 1
        return applied

    # ----------------------------------------------------------- routing

    def _available(self, exclude=()) -> tuple:
        """Constructions routing may use right now: not excluded, and
        circuit breaker closed.  Visiting an open breaker runs its
        half-open re-probe when ``reset_s`` has elapsed — recovery is
        checked on the routing path itself, no background poller.  When
        every construction is excluded/open the router DEGRADES rather
        than refuses: all non-excluded constructions are returned (a
        guess at a broken engine still beats a guaranteed error)."""
        avail = []
        for lb in self.constructions:
            if lb in exclude:
                continue
            br = self.breakers[lb]
            if not br.available() and br.should_probe():
                self._probe_breaker(lb)
            if br.available():
                avail.append(lb)
        if not avail:
            avail = [lb for lb in self.constructions
                     if lb not in exclude] or list(self.constructions)
        return tuple(avail)

    def _probe_breaker(self, lb: str) -> None:
        """Half-open re-probe: one timed dispatch per bucket through the
        (possibly rebuilt) engine.  Success refreshes the cost model for
        every bucket AND closes the breaker; failure re-opens it (fresh
        timer) and, on ``EngineDead``, wakes the supervisor."""
        try:
            for size, dt in self.engines[lb].probe(reps=1).items():
                self._observe(lb, size, dt)
        except Exception as e:
            self.breakers[lb].record_failure()
            if isinstance(e, EngineDead) and self.supervisor is not None:
                self.supervisor.notify(lb)
        else:
            self.breakers[lb].record_success()

    def _note_failure(self, lb: str, exc: BaseException) -> None:
        """Engine fault bookkeeping shared by submit/result paths."""
        self.breakers[lb].record_failure()
        if isinstance(exc, EngineDead) and self.supervisor is not None:
            self.supervisor.notify(lb)

    def _note_success(self, lb: str) -> None:
        self.breakers[lb].record_success()

    def dispatch_kernel_info(self, lb: str, bucket: int) -> dict:
        """The per-dispatch kernel decision the construction's server
        would resolve at this bucket: ``kernel_impl`` plus — when the
        resolver reports them — ``kernel_resolved_from`` provenance
        ("searched" for a tune/kernel_search variant) and
        ``row_chunk_effective`` (the chunk the Pallas grid kernel will
        actually run after its VMEM cell cap) / ``chunk_leaves_effective``
        (the GGM chunk after the live-seed budget clamp; surfacing them
        on route events is what keeps a clamped chunk from being an
        invisible different kernel than the cache entry claims).  Empty dict when
        the server doesn't expose a resolution.  Cheap:
        ``resolved_eval_knobs`` memoizes its tuning lookup per batch
        size."""
        try:
            eng = self.engines.get(lb)
            rk = getattr(getattr(eng, "_server", None),
                         "resolved_eval_knobs", None)
            if callable(rk):
                kn = rk(bucket)
                info = {"kernel_impl": kn.get("kernel_impl")}
                for extra in ("kernel_resolved_from",
                              "row_chunk_effective",
                              "chunk_leaves_effective"):
                    if kn.get(extra) is not None:
                        info[extra] = kn[extra]
                return info
        except Exception as e:  # diagnostics must never break routing
            note_swallowed("serve.router.dispatch_kernel", e)
        return {}

    # ----------------------------------------- arrival-rate estimator

    def note_arrival(self, bucket: int, t: float | None = None) -> None:
        """Feed one arrival at ``bucket`` into the live per-bucket
        arrival-rate estimator: an EWMA over inter-arrival gaps (same
        ``ewma_alpha`` as the cost model).  ``route`` calls this on
        every batch; ``t`` defaults to ``time.monotonic()`` — tests and
        replays pass explicit timestamps, making the estimate a pure
        function of the arrival sequence."""
        if t is None:
            t = time.monotonic()
        prev = self._arrivals.get(bucket)
        if prev is None:
            self._arrivals[bucket] = (t, None)
            return
        last_t, gap = prev
        new_gap = max(t - last_t, 1e-9)
        if gap is not None:
            new_gap = (self.ewma_alpha * new_gap
                       + (1 - self.ewma_alpha) * gap)
        self._arrivals[bucket] = (t, new_gap)

    def arrival_rate(self, bucket: int) -> float | None:
        """EWMA arrivals/second at ``bucket`` (None until two arrivals
        have been seen there)."""
        rec = self._arrivals.get(bucket)
        return None if rec is None or rec[1] is None else 1.0 / rec[1]

    def arrival_rates(self) -> dict:
        """The live per-bucket arrival-rate estimate ``{bucket: Hz}`` —
        what the registry's ``GranulePrefetcher`` consumes to size its
        between-arrivals page-in window (the offline twin over a full
        trace is ``loadgen.bucket_rates``).  Buckets seen fewer than
        twice are omitted."""
        return {bk: 1.0 / gap
                for bk, (_, gap) in sorted(self._arrivals.items())
                if gap is not None}

    def dispatch_kernel(self, lb: str, bucket: int) -> str | None:
        """The bare ``kernel_impl`` of :meth:`dispatch_kernel_info`
        (kept as the EWMA cost-table metrics label so a relay-TPU
        ``--load`` run can attribute latency shifts to kernel
        selection)."""
        return self.dispatch_kernel_info(lb, bucket).get("kernel_impl")

    def route(self, batch: int, exclude=()) -> RouteDecision:
        """Pick the construction for a ``batch``-query arrival.

        Cost-model routing needs an estimate for EVERY available
        construction at the batch's bucket (comparing a measured
        construction against unmeasured ones would lock onto whichever
        happened to be observed first); anything less falls back to the
        sticky cached winner — cold tuning cache included, where the
        sticky answer is the heuristic and ``routed_from`` says so.
        Every ~``EXPLORE_EVERY`` routes at a bucket, the construction
        whose estimate is stalest gets the batch instead of the argmin
        (``routed_from="explore"``) so its EWMA re-measures and a
        poisoned estimate self-corrects.

        ``exclude`` names constructions this call must avoid (failover
        after their engine faulted); open circuit breakers are excluded
        automatically.  When the sticky winner itself is unavailable
        the cheapest available construction answers instead with
        ``routed_from="failover"``.
        """
        if batch < 1:
            raise ValueError("batch must be >= 1 (got %d)" % batch)
        with span("route", batch=batch):
            bucket = (self.buckets.bucket_for(batch)
                      if batch <= self.buckets.max else self.buckets.max)
            self.note_arrival(bucket)
            avail = self._available(exclude)
            costs = {lb: self._costs.get((lb, bucket)) for lb in avail}
            if all(c is not None for c in costs.values()):
                for lb in avail:
                    self._obs_age[(lb, bucket)] = (
                        self._obs_age.get((lb, bucket), 0) + 1)
                stalest = max(avail,
                              key=lambda lb: self._obs_age[(lb, bucket)])
                if self._obs_age[(stalest, bucket)] >= self.EXPLORE_EVERY:
                    label, routed_from = stalest, "explore"
                    # reset the clock at ROUTE time, not observation
                    # time: with deferred result() every in-flight route
                    # at this bucket would otherwise re-trigger the same
                    # explore — a window-sized storm of the
                    # possibly-slowest construction mid-burst
                    self._obs_age[(stalest, bucket)] = 0
                else:
                    label = min(costs, key=costs.get)
                    routed_from = "cost-model"
            elif self.sticky in avail:
                label, routed_from = (self.sticky,
                                      self.sticky_resolved_from)
            else:
                # sticky winner is down: cheapest available estimate,
                # else first available — provenance says failover
                known = {lb: c for lb, c in costs.items()
                         if c is not None}
                label = (min(known, key=known.get) if known
                         else avail[0])
                routed_from = "failover"
            self.routed_from = routed_from
            self.route_counts[label] += 1
            self.routed_from_counts[routed_from] = (
                self.routed_from_counts.get(routed_from, 0) + 1)
            # the winning construction's per-dispatch kernel decision
            # (impl + searched/halved provenance) — fault/latency
            # attribution joins on it
            kinfo = self.dispatch_kernel_info(label, bucket)
            ev = {"construction": label, "routed_from": routed_from,
                  "bucket": bucket, "batch": batch,
                  "kernel_impl": kinfo.get("kernel_impl"),
                  "costs_ms": {lb: (None if c is None
                                    else round(c * 1e3, 4))
                               for lb, c in costs.items()}}
            for extra in ("kernel_resolved_from", "row_chunk_effective",
                          "chunk_leaves_effective"):
                if kinfo.get(extra) is not None:
                    ev[extra] = kinfo[extra]
            if self.injector is not None:
                # the arrival index FaultInjector events carry too —
                # the join key for fault -> route attribution
                ev["arrival"] = self.injector.arrival
            if self.tenant is not None:
                ev["tenant"] = self.tenant
            FLIGHT.record("route", **ev)
            return RouteDecision(label, routed_from, bucket, batch)

    def submit(self, decision: RouteDecision, keys) -> RoutedFuture:
        """Dispatch ``keys`` (minted for ``decision.construction`` —
        ``server(label).gen``) through that construction's engine;
        returns a ``RoutedFuture`` whose resolution feeds the observed
        service time back into the cost model.  Engine faults (anything
        but the ``LoadShed``/``DeadlineExceeded`` admission decisions)
        count against the construction's circuit breaker before
        re-raising; ``EngineDead`` additionally wakes the supervisor."""
        engine = self.engines[decision.construction]
        chunks = len(engine.buckets.chunks(len(keys)))
        t0 = time.perf_counter()
        try:
            fut = engine.submit(keys)
        except (LoadShed, DeadlineExceeded, EngineClosed):
            raise               # admission decisions, not engine faults
        except Exception as e:
            self._note_failure(decision.construction, e)
            raise
        return RoutedFuture(self, fut, decision, t0, chunks)

    def submit_resilient(self, batch: int, keys_for, *, retry=None,
                         exclude=()) -> RoutedFuture:
        """Route + submit with retry AND construction failover.

        ``keys_for(label)`` mints/fetches the keys for a construction
        (keys are construction-specific, so failover must re-mint).
        Each attempt routes fresh — ``EngineDead`` (and any breaker
        opened by earlier failures) excludes that construction, so the
        retry lands on a healthy engine over the same table; transient
        faults retry the same construction after the policy's backoff.
        Counts ``recovery.retries`` per re-attempt and
        ``recovery.failovers`` when the construction changed.
        ``LoadShed``/``DeadlineExceeded`` propagate immediately (never
        retried).  The returned future resolves the SUCCESSFUL submit;
        failures surfacing later in ``result()`` are the caller's to
        handle (resolution happens outside this call's scope).
        """
        policy = retry or self.retry or RetryPolicy()
        excluded = set(exclude)
        last_label = None
        attempt = 0
        while True:
            attempt += 1
            decision = self.route(batch, exclude=excluded)
            failed_over = (last_label is not None
                           and decision.construction != last_label)
            if failed_over:
                self.recovery.inc("failovers")
                fev = dict(frm=last_label, to=decision.construction,
                           batch=batch, attempt=attempt)
                if self.tenant is not None:
                    fev["tenant"] = self.tenant
                FLIGHT.record("failover", **fev)
            last_label = decision.construction
            try:
                if attempt == 1:
                    return self.submit(decision,
                                       keys_for(decision.construction))
                # re-attempts get their own span ("failover" when the
                # construction changed) so recovery time is attributable
                with span("failover" if failed_over else "retry",
                          attempt=attempt,
                          construction=decision.construction):
                    return self.submit(decision,
                                       keys_for(decision.construction))
            except (LoadShed, DeadlineExceeded, EngineClosed):
                raise
            except Exception as e:
                if (not policy.retryable(e)
                        or attempt >= policy.max_attempts):
                    raise
                self.recovery.inc("retries")
                rev = dict(construction=decision.construction,
                           batch=batch, attempt=attempt,
                           error=type(e).__name__)
                if self.tenant is not None:
                    rev["tenant"] = self.tenant
                FLIGHT.record("retry", **rev)
                if isinstance(e, EngineDead):
                    # dead engines don't heal within a backoff window:
                    # fail over NOW, no sleep
                    excluded.add(decision.construction)
                    if len(excluded) >= len(self.constructions):
                        excluded.clear()   # everything down: retry all
                        policy.sleep(attempt)
                else:
                    policy.sleep(attempt)

    # ---------------------------------------------------------- plumbing

    def server(self, label: str):
        """The prepared ``api.DPF`` serving one construction (also the
        key-minting client and the scalar-oracle reference for it)."""
        return self._servers[label]

    def warmup(self, probe: bool = True, probe_reps: int = 1) -> None:
        """Precompile every (construction, bucket) program; with
        ``probe`` also seed the cost model from one timed dispatch each
        (``ServingEngine.probe``)."""
        for lb, engine in self.engines.items():
            engine.warmup()
            if probe:
                for size, dt in engine.probe(reps=probe_reps).items():
                    self._observe(lb, size, dt)

    def drain(self) -> None:
        """Resolve every outstanding dispatch across all engines."""
        for engine in self.engines.values():
            engine.drain()

    def close(self) -> None:
        """Drain, then decommission every engine: in-flight work
        completes, and any later ``submit`` is rejected with the
        engine's ``EngineClosed`` (passed through untouched — a closed
        engine is a decision, not a fault, so it never counts against
        a breaker).  Outstanding supervisor rebuilds are joined first
        so a rebuilt engine cannot resurrect a closed construction."""
        if self.supervisor is not None:
            self.supervisor.join()
        for engine in self.engines.values():
            engine.close()

    def reset_counters(self) -> None:
        """Zero routing counts and every engine's counters (bench reps
        measure fresh); the LEARNED state — the cost model and sticky
        resolution — is kept."""
        for engine in self.engines.values():
            engine.stats.reset()
        self.recovery.reset()
        self.route_counts = {lb: 0 for lb in self.constructions}
        self.routed_from_counts = {}

    def counters(self) -> EngineCounters:
        """All engines' counters merged into one record
        (``EngineCounters.merge``), plus the router-level recovery
        events (retries/failovers/breaker opens/restarts) — the
        router-level SLO view."""
        agg = EngineCounters()
        for engine in self.engines.values():
            agg.merge(engine.stats)
        agg.merge(self.recovery)
        return agg

    def stats(self) -> dict:
        """Routing + serving diagnostics for benchmark records."""
        out = {
            "constructions": list(self.constructions),
            "sticky": self.sticky,
            "sticky_resolved_from": self.sticky_resolved_from,
            "routed_from": self.routed_from,
            "route_counts": dict(self.route_counts),
            "routed_from_counts": dict(self.routed_from_counts),
            "cost_model_ms": {
                "%s@%d" % (lb, bk): round(s * 1e3, 4)
                for (lb, bk), s in sorted(self._costs.items())},
            "buckets": list(self.buckets.sizes),
            "arrival_rate_hz": {
                "%d" % bk: round(hz, 4)
                for bk, hz in self.arrival_rates().items()},
            "counters": self.counters().as_dict(),
            "per_engine": {lb: e.stats.as_dict()
                           for lb, e in self.engines.items()},
            "breakers": {lb: br.as_dict()
                         for lb, br in self.breakers.items()},
        }
        if self.supervisor is not None:
            out["supervisor"] = {
                "failed_rebuilds": self.supervisor.failed_rebuilds,
                "rebuilding": list(self.supervisor.rebuilding())}
        if self.injector is not None:
            out["faults"] = self.injector.stats()
        return out

    def __repr__(self):
        return ("SchemeRouter(n=%d, constructions=%s, sticky=%s/%s, "
                "routed=%s)" % (self.n, list(self.constructions),
                                self.sticky, self.sticky_resolved_from,
                                dict(self.route_counts)))
