"""Streaming serving benchmark: blocking eval_tpu loop vs ServingEngine.

Measures sustained queries/sec over a stream of query batches — the
serving engine's headline — plus the vectorized-ingest micro-benchmark
(scalar per-key codec vs the batched codec at B=512).  Prints ONE JSON
line with the same record shape as ``bench.py`` (metric/value/unit/
vs_baseline); here the baseline is the blocking per-batch loop on the
identical key stream, gated on bit-exact result equality first.

Runs fine on ``JAX_PLATFORMS=cpu`` (the ingest and pipelining wins are
host-side and backend-independent; on the synchronous CPU backend the
engine's win is the vectorized ingest + bucket reuse, on TPU async
dispatch adds the host/device overlap on top).

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python -m dpf_tpu.serve.bench_serve [--out FILE]
"""

from __future__ import annotations

import json
import time

import numpy as np


def ingest_microbench(B=512, n=65536, distinct=32, reps=5):
    """Scalar per-key codec loop vs the batched codec on one key batch.

    Returns {scalar_s, batched_s, speedup, ...}; both paths produce the
    packed (cw1, cw2, last) arrays and are asserted bit-identical before
    timing.
    """
    from ..core import expand, keygen

    ks = []
    for i in range(distinct):
        k0, _ = keygen.generate_keys((i * 0x9E3779B1) % n, n,
                                     b"ingest-%d" % i, prf_method=0)
        ks.append(k0.serialize())
    keys = [ks[i % distinct] for i in range(B)]

    flat = [keygen.deserialize_key(k) for k in keys]
    scalar = expand.pack_keys(flat)
    pk = keygen.decode_keys_batched(keys)
    assert (np.array_equal(scalar[0], pk.cw1)
            and np.array_equal(scalar[1], pk.cw2)
            and np.array_equal(scalar[2], pk.last)), \
        "batched codec diverged from the scalar oracle"

    t0 = time.perf_counter()
    for _ in range(reps):
        expand.pack_keys([keygen.deserialize_key(k) for k in keys])
    scalar_s = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        keygen.decode_keys_batched(keys)
    batched_s = (time.perf_counter() - t0) / reps

    return {"batch": B, "entries": n, "reps": reps,
            "scalar_s": round(scalar_s, 6),
            "batched_s": round(batched_s, 6),
            "speedup": round(scalar_s / batched_s, 2)}


def sqrt_ingest_microbench(B=512, n=65536, distinct=32, reps=5):
    """Scalar per-key sqrt-N codec loop vs the batched codec
    (``sqrtn.decode_sqrt_keys_batched``) on one key batch — the sqrt-N
    counterpart of ``ingest_microbench``, same record shape; asserted
    bit-identical before timing."""
    from ..core import sqrtn

    ks = []
    for i in range(distinct):
        k0, _ = sqrtn.generate_sqrt_keys((i * 0x9E3779B1) % n, n,
                                         b"sq-ingest-%d" % i, prf_method=0)
        ks.append(k0.serialize())
    keys = [ks[i % distinct] for i in range(B)]

    scalar = sqrtn.pack_sqrt_keys([sqrtn.deserialize_sqrt_key(k)
                                   for k in keys])
    pk = sqrtn.decode_sqrt_keys_batched(keys)
    assert (np.array_equal(scalar[0], pk.seeds)
            and np.array_equal(scalar[1], pk.cw1)
            and np.array_equal(scalar[2], pk.cw2)
            and pk.n == n), \
        "batched sqrt-N codec diverged from the scalar oracle"

    t0 = time.perf_counter()
    for _ in range(reps):
        sqrtn.pack_sqrt_keys([sqrtn.deserialize_sqrt_key(k)
                              for k in keys])
    scalar_s = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        sqrtn.decode_sqrt_keys_batched(keys)
    batched_s = (time.perf_counter() - t0) / reps

    return {"batch": B, "entries": n, "reps": reps,
            "scalar_s": round(scalar_s, 6),
            "batched_s": round(batched_s, 6),
            "speedup": round(scalar_s / batched_s, 2)}


def _key_stream(dpf, n, batch, batches, distinct=16, ragged=False):
    """A deterministic stream of key batches (server-0 keys)."""
    ks = [dpf.gen((i * 0x9E3779B1) % n, n, seed=b"serve-%d" % i)[0]
          for i in range(distinct)]
    sizes = []
    for j in range(batches):
        if ragged:
            sizes.append(max(1, batch >> (j % 3)))  # batch, b/2, b/4, ...
        else:
            sizes.append(batch)
    return [[ks[(j + i) % distinct] for i in range(b)]
            for j, b in enumerate(sizes)]


def _blocking_scalar_pass(dpf, stream):
    """The pre-engine serial serving path, as one round of this PR found
    it: per-key scalar deserialize + per-key pack, dispatch, block.  The
    record's headline baseline — the loop the engine replaces."""
    from ..core import expand, keygen
    outs = []
    for batch in stream:
        flat = [keygen.deserialize_key(k) for k in batch]
        cw1, cw2, last = expand.pack_keys(flat)
        pk = keygen.PackedKeys(cw1, cw2, last,
                               depth=flat[0].depth, n=flat[0].n)
        outs.append(np.asarray(dpf._dispatch_packed(pk)))
    return outs


def stream_bench(n=1024, entry_size=16, batch=256, batches=24, prf=None,
                 max_in_flight=2, ragged=False, quiet=False):
    """Sustained-throughput A/B/C on one streamed workload.

    Three passes over the identical key stream, equality-gated:

    * ``blocking_scalar`` — the pre-engine serial path (per-key codec
      loop + dispatch + block): the PR's baseline, ``vs_baseline``.
    * ``blocking`` — today's ``eval_tpu`` loop (already on the batched
      codec) — isolates what the pipelining/bucketing adds on top of
      the vectorized ingest (``vs_blocking_batched``).
    * the ``ServingEngine`` — ``value`` is its sustained queries/sec.

    On a multi-core host / real accelerator the engine additionally
    overlaps host packing with device execution; on a 1-core CPU the
    win is the ingest + bounded-shape reuse alone.
    """
    import dpf_tpu

    if prf is None:
        prf = dpf_tpu.PRF_DUMMY  # host-path-bound config: the serving
        #        engine's target regime (device math fast, ingest hot)
    dpf = dpf_tpu.DPF(prf=prf)
    table = np.random.default_rng(3).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    dpf.eval_init(table)
    stream = _key_stream(dpf, n, batch, batches, ragged=ragged)
    total = sum(len(b) for b in stream)

    # warm every shape both paths will compile, outside the timed region
    engine = dpf.serving_engine(max_in_flight=max_in_flight, warmup=True)
    for b in {len(s) for s in stream}:
        np.asarray(dpf.eval_tpu(stream[0][:b]))

    # correctness gate: all three passes bit-identical on the stream
    blocking_ref = [np.asarray(dpf.eval_tpu(b)) for b in stream]
    scalar_ref = _blocking_scalar_pass(dpf, stream)
    futs = [engine.submit(b) for b in stream]
    engine.drain()
    for ref, sc, fut in zip(blocking_ref, scalar_ref, futs):
        if not (np.array_equal(ref, fut.result())
                and np.array_equal(ref, sc)):
            raise AssertionError("serving passes diverged")

    t0 = time.perf_counter()
    _blocking_scalar_pass(dpf, stream)
    scalar_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for b in stream:
        np.asarray(dpf.eval_tpu(b))
    blocking_s = time.perf_counter() - t0

    # pipelined engine, fresh stats
    engine = dpf.serving_engine(max_in_flight=max_in_flight, warmup=True)
    t0 = time.perf_counter()
    futs = [engine.submit(b) for b in stream]
    engine.drain()
    engine_s = time.perf_counter() - t0

    micro = ingest_microbench()
    qps_engine = total / engine_s
    qps_blocking = total / blocking_s
    qps_scalar = total / scalar_s
    record = {
        "metric": "sustained queries/sec (serving engine, entries=%d, "
                  "entry_size=%d, %s, stream %dx%d%s, 1 device)"
                  % (n, entry_size, dpf.prf_method_string, batches, batch,
                     " ragged" if ragged else ""),
        "value": int(qps_engine),
        "unit": "queries/sec",
        "vs_baseline": round(qps_engine / qps_scalar, 4),
        "baseline": "pre-engine blocking loop (per-key scalar codec + "
                    "dispatch + block), identical stream",
        "blocking_scalar_qps": int(qps_scalar),
        "blocking_scalar_elapsed_s": round(scalar_s, 4),
        "blocking_qps": int(qps_blocking),
        "blocking_elapsed_s": round(blocking_s, 4),
        "vs_blocking_batched": round(qps_engine / qps_blocking, 4),
        "engine_elapsed_s": round(engine_s, 4),
        "max_in_flight": max_in_flight,
        "buckets": list(engine.buckets.sizes),
        # the effective program shape (bucket ladder, in-flight window,
        # dot_impl, chunk_leaves, ...), so BENCH_* files are
        # self-describing about what actually ran
        "resolved_config": engine.resolved_config(),
        "engine_stats": engine.stats.as_dict(),
        "ingest_microbench": micro,
        "checked": True,  # bit-exact equality gate ran before timing
    }
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--batches", type=int, default=24)
    ap.add_argument("--prf", type=int, default=None,
                    help="PRF id (default DUMMY; 2=ChaCha20, 3=AES128)")
    ap.add_argument("--max-in-flight", type=int, default=2)
    ap.add_argument("--ragged", action="store_true",
                    help="cycle ragged batch sizes (exercises buckets)")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    record = stream_bench(n=args.n, entry_size=args.entry_size,
                          batch=args.batch, batches=args.batches,
                          prf=args.prf, max_in_flight=args.max_in_flight,
                          ragged=args.ragged)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
