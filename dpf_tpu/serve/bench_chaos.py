"""Chaos benchmark: availability under seeded fault injection.

``benchmark.py --chaos``.  Replays the SAME seeded bursty trace the
load benchmark uses (``serve/loadgen.py``) through a fault-tolerant
router stack (``SchemeRouter`` + ``RetryPolicy`` + per-construction
circuit breakers + ``EngineSupervisor``) under escalating fault plans
(``serve/faults.py``):

* **baseline** — no faults: the availability reference for this
  machine/trace (what the recovery legs must stay close to).
* **faults**   — ≥10% injected dispatch failures across every
  construction, latency spikes, and silently corrupted result shares.
* **chaos**    — the faults leg PLUS a full engine death: the
  cost-model favorite construction is killed mid-trace; its traffic
  must fail over to the healthy engines over the same table while the
  supervisor rebuilds it in the background and the circuit breaker
  walks open → half-open → closed.

**Availability** is the correct-within-SLO fraction: an arrival counts
only if its batch was served, bit-gated against the scalar oracle
(``DPF.eval_cpu`` reference shares, checked inline before the client
accepts the answer), and completed within the SLO measured from its
*scheduled* arrival time.  The inline gate doubles as the corruption
detector: every injected share corruption must be caught and the batch
re-served (``corruptions_detected`` == injected, ``gate_escapes`` ==
0), proving the equality gate is an integrity check, not just a test
assertion.

Every injection decision is deterministic under the plan seed (see
``faults.FaultInjector``), so the committed record —
``BENCH_CHAOS_r11.json`` — replays the identical fault sequence on the
identical trace.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --chaos [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..core.expand import DeadlineExceeded
from ..obs import FLIGHT, flight_dump, record_sections
from ..utils.profiling import swallowed_snapshot
from .bench_load import _batch_for, _key_pool, _slo_stats, replay
from .engine import LoadShed
from .faults import FaultPlan, FaultSpec, RetryPolicy
from . import loadgen


class _FailedBatch:
    """Future-shaped sentinel for an arrival whose serve attempts were
    exhausted: the replay loop resolves it like any future, the
    availability accounting counts it unavailable."""
    ok = False

    def done(self) -> bool:
        return True

    def result(self):
        return None


class _VerifiedFuture:
    """A routed future whose ``result()`` is the full client protocol:
    resolve, bit-gate against the scalar-oracle references, and on a
    failed gate (an injected corruption) or a resolve-time fault,
    RE-SERVE the batch through ``SchemeRouter.submit_resilient`` — up
    to ``client.max_reserves`` times.  The re-serve cost lands in the
    measured latency (against the scheduled arrival), so corruption
    recovery is paid for inside the availability number, not hidden."""

    __slots__ = ("client", "a", "j", "fut", "ok", "_value")

    def __init__(self, client, a, j, fut):
        self.client = client
        self.a = a
        self.j = j
        self.fut = fut
        self.ok = None
        self._value = None

    def done(self) -> bool:
        return self.ok is not None or self.fut.done()

    def result(self):
        if self.ok is not None:
            return self._value
        c = self.client
        out = None
        for attempt in range(c.max_reserves + 1):
            try:
                out = np.asarray(self.fut.result())
            except (LoadShed, DeadlineExceeded):
                raise
            except Exception:
                out = None
            if out is not None:
                lb = self.fut.decision.construction
                _, idxs = _batch_for(c.pools[lb], self.j, self.a.batch)
                if np.array_equal(out, c.pools[lb][1][idxs]):
                    self.ok = True
                    self._value = out
                    return out
                c.detected_corruptions += 1
            if attempt >= c.max_reserves:
                break
            c.reserves += 1
            try:
                self.fut = c.router.submit_resilient(
                    self.a.batch, c.keys_for(self.j, self.a.batch))
            except Exception:
                break
        self.ok = False
        self._value = out
        c.failed_batches += 1
        return out


class _ChaosClient:
    """The submit side of one chaos leg: routes every arrival through
    ``submit_resilient`` (retry + failover) and wraps the future in the
    verify-and-reserve protocol above."""

    def __init__(self, router, pools, injector, *, max_reserves=3):
        self.router = router
        self.pools = pools
        self.injector = injector
        self.max_reserves = max_reserves
        self.detected_corruptions = 0
        self.failed_batches = 0
        self.reserves = 0

    def keys_for(self, j, b):
        return lambda lb: _batch_for(self.pools[lb], j, b)[0]

    def submit(self, a, j):
        if self.injector is not None:
            self.injector.begin_arrival(j)
        try:
            fut = self.router.submit_resilient(a.batch,
                                               self.keys_for(j, a.batch))
        except (LoadShed, DeadlineExceeded):
            raise
        except Exception:
            self.failed_batches += 1
            return _FailedBatch()
        return _VerifiedFuture(self, a, j, fut)


def _fault_specs(*, dispatch_p: float, latency_p: float,
                 latency_s: float, corrupt_p: float) -> list:
    return [
        FaultSpec(kind="dispatch_error", p=dispatch_p),
        FaultSpec(kind="latency", p=latency_p, latency_s=latency_s),
        FaultSpec(kind="corrupt_shares", p=corrupt_p),
    ]


def _favorite(router, cap: int) -> str:
    """The cost-model favorite at the cap bucket after probe seeding —
    the construction whose death hurts the most (its traffic is the
    argmin's first choice)."""
    costs = {lb: router.cost(lb, cap) for lb in router.constructions}
    known = {lb: c for lb, c in costs.items() if c is not None}
    return (min(known, key=known.get) if known
            else router.constructions[0])


def _run_leg(servers, cap, trace, pools, slo_s, window, plan, *,
             retry, breaker_failures, breaker_reset_s,
             reclose_wait_s=10.0) -> dict:
    """One replay of ``trace`` under ``plan`` through a fresh
    fault-tolerant router over the SHARED prepared servers; returns the
    leg record with availability + recovery accounting."""
    from .router import SchemeRouter
    inj = plan.injector() if plan is not None else None
    router = SchemeRouter(None, servers=servers, cap=cap, probe=True,
                          injector=inj, retry=retry,
                          breaker_failures=breaker_failures,
                          breaker_reset_s=breaker_reset_s,
                          supervise=True)
    client = _ChaosClient(router, pools, inj)
    lats, done, makespan, _, _ = replay(trace, client.submit,
                                        window=window)
    router.drain()
    if router.supervisor is not None:
        router.supervisor.join(timeout=reclose_wait_s)
    # give every still-open breaker its half-open re-probe: the routing
    # path itself is the recovery check, so route until settled (the
    # chaos leg's killed construction must re-close here at the latest
    # — usually it already did mid-trace)
    deadline = time.monotonic() + reclose_wait_s
    while (any(br.state != "closed" for br in router.breakers.values())
           and time.monotonic() < deadline):
        router.route(1)
        time.sleep(min(0.05, breaker_reset_s / 4))

    # ---- availability: correct-within-SLO over ALL trace arrivals ----
    # done[i] and lats[i] are appended together by the replay loop
    ok_in_slo = sum(1 for (_, _, fut), lat in zip(done, lats)
                    if getattr(fut, "ok", False) and lat <= slo_s)
    escapes = 0
    for a, j, fut in done:      # re-gate final values: escapes must be 0
        if not getattr(fut, "ok", False):
            continue
        lb = fut.fut.decision.construction
        _, idxs = _batch_for(pools[lb], j, a.batch)
        if not np.array_equal(fut.result(), pools[lb][1][idxs]):
            escapes += 1
    counters = router.counters()
    total = len(trace)
    rec = {
        "availability": round(ok_in_slo / total, 4) if total else None,
        "served_ok": ok_in_slo,
        "arrivals": total,
        "failed_batches": client.failed_batches,
        "reserves_after_gate": client.reserves,
        "makespan_s": round(makespan, 4),
        "qps": int(loadgen.total_queries(trace) / makespan)
        if makespan else None,
        **_slo_stats(lats, slo_s),
        "recovery": {
            "retries": counters.retries,
            "failovers": counters.failovers,
            "breaker_opens": counters.breaker_opens,
            "engine_restarts": counters.engine_restarts,
            "swallowed_errors": counters.swallowed_errors,
        },
        "breakers": {lb: br.as_dict()
                     for lb, br in router.breakers.items()},
        "route_counts": dict(router.route_counts),
    }
    if inj is not None:
        rec["faults"] = {
            "plan": plan.as_dict(),
            "injected": dict(inj.injected),
            "corruptions_injected": len(inj.corruptions),
            "corruptions_detected": client.detected_corruptions,
        }
    rec["gate_escapes"] = escapes
    return rec, router


def chaos_bench(n=4096, entry_size=16, cap=128, prf=0, *,
                seed=11, duration_s=6.0, on_rate=60.0, slo_ms=1000.0,
                dispatch_p=0.12, latency_p=0.05, latency_s=0.02,
                corrupt_p=0.03, window=8, distinct=16,
                breaker_failures=2, breaker_reset_s=0.4,
                quiet=False) -> dict:
    """Escalating fault plans over one seeded bursty trace; returns the
    ``--chaos`` record (``BENCH_CHAOS_r11.json``)."""
    from .router import LABELS, build_servers

    FLIGHT.clear()      # scope the embedded flight tail to this bench
    table = np.random.default_rng(seed ^ 0xc4a05).integers(
        0, 2 ** 31, (n, entry_size), dtype=np.int32, endpoint=False)
    trace = loadgen.bursty_trace(
        on_rate=on_rate, off_rate=2.0, on_s=1.0, off_s=2.0,
        duration_s=duration_s, cap=cap, seed=seed, n=n)
    slo_s = slo_ms / 1e3
    retry = RetryPolicy(max_attempts=4, backoff_s=0.002, seed=seed)

    # one table upload + key pool + oracle reference per construction,
    # shared by every leg (the legs differ ONLY in their fault plan)
    servers = build_servers(table, LABELS, prf_method=prf)
    pools = {lb: _key_pool(servers[lb], n, distinct,
                           b"chaos-%s" % lb.encode())
             for lb in LABELS}
    leg_kw = dict(retry=retry, breaker_failures=breaker_failures,
                  breaker_reset_s=breaker_reset_s)

    # ---- leg 1: baseline (no faults) ---------------------------------
    baseline, _ = _run_leg(servers, cap, trace, pools, slo_s, window,
                           FaultPlan((), seed=seed), **leg_kw)

    # ---- leg 2: dispatch errors + stragglers + corrupted shares ------
    fault_plan = FaultPlan(_fault_specs(
        dispatch_p=dispatch_p, latency_p=latency_p,
        latency_s=latency_s, corrupt_p=corrupt_p), seed=seed)
    faults_leg, fr = _run_leg(servers, cap, trace, pools, slo_s,
                              window, fault_plan, **leg_kw)

    # ---- leg 3: + full engine death of the cost-model favorite -------
    victim = _favorite(fr, fr.buckets.max)
    kill_at = max(1, len(trace) // 3)
    chaos_plan = FaultPlan(_fault_specs(
        dispatch_p=dispatch_p, latency_p=latency_p,
        latency_s=latency_s, corrupt_p=corrupt_p)
        + [FaultSpec(kind="engine_death", construction=victim,
                     start=kill_at)], seed=seed)
    chaos_leg, cr = _run_leg(servers, cap, trace, pools, slo_s, window,
                             chaos_plan, **leg_kw)
    chaos_leg["victim"] = victim
    chaos_leg["killed_at_arrival"] = kill_at
    victim_states = [s for _, s in cr.breakers[victim].transitions]
    chaos_leg["victim_breaker_transitions"] = victim_states

    total_escapes = (baseline["gate_escapes"] + faults_leg["gate_escapes"]
                     + chaos_leg["gate_escapes"])
    record = {
        "metric": "fault-tolerant serving: availability (correct-"
                  "within-SLO fraction) under escalating seeded fault "
                  "plans — %.0f%% dispatch failures + stragglers + "
                  "corrupted shares + one engine death (entries=%d, "
                  "entry_size=%d, prf=%d, bursty trace: %d arrivals / "
                  "%d queries, cap=%d, slo=%dms, 1 device)"
                  % (dispatch_p * 100, n, entry_size, prf, len(trace),
                     loadgen.total_queries(trace), cap, int(slo_ms)),
        "value": chaos_leg["availability"],
        "unit": "availability",
        "vs_baseline": (round(chaos_leg["availability"]
                              / baseline["availability"], 4)
                        if baseline["availability"] else None),
        "baseline": "the identical router stack replaying the identical"
                    " seeded trace with no fault plan",
        "slo_ms": slo_ms,
        "trace": {"kind": "bursty", "seed": seed,
                  "duration_s": duration_s, "on_rate": on_rate,
                  "arrivals": len(trace),
                  "queries": loadgen.total_queries(trace),
                  "cap": cap, "window": window},
        "retry_policy": {"max_attempts": retry.max_attempts,
                         "backoff_s": retry.backoff_s,
                         "backoff_mult": retry.backoff_mult,
                         "jitter": retry.jitter, "seed": retry.seed},
        "breaker": {"failures": breaker_failures,
                    "reset_s": breaker_reset_s},
        "baseline_leg": baseline,
        "faults_leg": faults_leg,
        "chaos_leg": chaos_leg,
        "swallowed_errors": swallowed_snapshot(),
        "gate_escapes": total_escapes,
        "checked": bool(
            total_escapes == 0
            and chaos_leg["availability"] is not None
            and chaos_leg["availability"] >= 0.99
            and chaos_leg["recovery"]["engine_restarts"] >= 1
            and victim_states[-1] == "closed"),
    }
    record["obs"] = record_sections()
    if not record["checked"]:
        # a failed gate is exactly what the flight recorder exists to
        # diagnose: embed the FULL ring (route decisions, breaker walk,
        # every injected fault with its arrival join key)
        record["obs"]["flight_on_gate_failure"] = flight_dump()
        import sys
        print("chaos gate FAILED — full flight dump embedded in record"
              " (obs.flight_on_gate_failure, %d events)"
              % len(record["obs"]["flight_on_gate_failure"]),
              file=sys.stderr, flush=True)
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--duration", type=float, default=6.0,
                    help="trace duration in seconds")
    ap.add_argument("--on-rate", type=float, default=60.0,
                    help="burst arrival rate (arrivals/sec in ON "
                         "windows)")
    ap.add_argument("--slo-ms", type=float, default=1000.0)
    ap.add_argument("--dispatch-p", type=float, default=0.12,
                    help="per-dispatch injected failure probability")
    ap.add_argument("--corrupt-p", type=float, default=0.03,
                    help="per-batch injected share-corruption "
                         "probability")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): exercises every "
                         "leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.dryrun:
        record = chaos_bench(n=512, entry_size=8, cap=16, prf=args.prf,
                             seed=args.seed, duration_s=1.5,
                             on_rate=20.0, slo_ms=args.slo_ms,
                             dispatch_p=args.dispatch_p,
                             corrupt_p=args.corrupt_p, distinct=8,
                             breaker_reset_s=0.2)
    else:
        record = chaos_bench(n=args.n, entry_size=args.entry_size,
                             cap=args.cap, prf=args.prf, seed=args.seed,
                             duration_s=args.duration,
                             on_rate=args.on_rate, slo_ms=args.slo_ms,
                             dispatch_p=args.dispatch_p,
                             corrupt_p=args.corrupt_p)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
