"""Throughput-oriented serving subsystem.

``ServingEngine`` (engine.py) pipelines host packing against device
execution under a bounded in-flight window; ``Buckets`` (buckets.py)
bounds the compiled-program count under ragged batch sizes;
``bench_serve.py`` measures sustained queries/sec for the blocking loop
vs. the engine.  Constructed via ``DPF.serving_engine()`` or
``ShardedDPFServer.serving_engine()``.
"""

from .buckets import Buckets  # noqa: F401
from .engine import EngineFuture, ServingEngine  # noqa: F401
