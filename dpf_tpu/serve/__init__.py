"""Throughput-oriented serving subsystem.

``ServingEngine`` (engine.py) pipelines host packing against device
execution under a bounded in-flight window, with cooperative monotonic
deadlines, a latency ring, and admission control (``LoadShed``);
``Buckets`` (buckets.py) bounds the compiled-program count under ragged
batch sizes; ``loadgen.py`` generates deterministic open-loop arrival
traces (Poisson / bursty / diurnal / replay); ``SchemeRouter``
(router.py) dispatches each arriving batch to the cheapest construction
by a live cost model; ``faults.py`` supplies seeded fault injection
(``FaultPlan``/``FaultInjector``) and the recovery machinery
(``RetryPolicy``, ``CircuitBreaker``, ``EngineSupervisor``) the router
wires together; ``bench_serve.py`` measures sustained queries/sec
for the blocking loop vs. the engine, ``bench_load.py`` races the
router against the sticky baseline under a traffic trace with SLO
accounting, and ``bench_chaos.py`` replays that trace under escalating
fault plans to measure availability.  Constructed via
``DPF.serving_engine()`` or ``ShardedDPFServer.serving_engine()``.

The multi-tenant tier sits on top: ``TableRegistry`` (registry.py)
holds named, versioned tables with LRU device residency against a byte
budget, ``TenantRouter`` (tenant.py) runs one isolated ``SchemeRouter``
per tenant (per-tenant breakers/admission/SLO, tenant-labeled
flight/metrics) under a weighted-fair deficit-round-robin scheduler,
and ``bench_multitenant.py`` gates the noisy-neighbor isolation claim
(``benchmark.py --multitenant``).
"""

from .buckets import Buckets  # noqa: F401
from .engine import EngineFuture, LoadShed, ServingEngine  # noqa: F401
from .faults import (CircuitBreaker, EngineDead, EngineSupervisor,  # noqa: F401
                     FaultError, FaultInjector, FaultPlan, FaultSpec,
                     InjectedCompileError, InjectedDispatchError,
                     RetryPolicy, submit_with_retry)
from .loadgen import Arrival, make_trace  # noqa: F401
from .registry import TableLease, TableRegistry, TableVersion  # noqa: F401
from .router import RouteDecision, SchemeRouter  # noqa: F401
from .tenant import TenantFuture, TenantRouter, TenantSpec  # noqa: F401
