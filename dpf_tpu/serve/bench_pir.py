"""End-to-end batch-PIR benchmark: plan -> keygen -> answer -> recover.

Measures the production batch-PIR path (this PR) against the pre-PR
machinery on the identical planned workload, equality-gated before any
timing:

* **keygen** — ``PrivateLookupClient.make_queries`` (one vectorized
  ``gen_batched`` call per (n, G) size group) vs ``make_queries_scalar``
  (the per-bin ``DPF.gen`` Python loop), byte-identical keys under
  pinned DRBG seeds.
* **answer** — ``PrivateLookupServer.answer`` (packed wire codecs,
  tuning-cache knobs, every size group dispatched asynchronously before
  one blocking gather) vs ``answer_scalar`` (per-key deserialize,
  frozen heuristics, per-group host sync), bit-identical shares.
* **end-to-end** — keygen -> answer(A) + answer(B) -> recover over
  ``rounds`` query rounds, both paths.
* **streaming** — the same rounds pipelined through ``LookupStream``
  (one ServingEngine per size group) on both servers.

Runs fine on ``JAX_PLATFORMS=cpu`` (the keygen and ingest levers are
host-side; on TPU the async group dispatch and the stream's in-flight
window add device overlap on top).

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --batch-pir [--out BENCH_PIR_r09.json]
"""

from __future__ import annotations

import json
import time

import numpy as np


def _workload(entries, entry_size, bin_fraction, seed=0):
    """Deterministic planned workload: a table, access patterns binning
    EVERY entry (chunked coverage patterns — the planner only bins
    indices it has seen), and the optimizer's plan over them."""
    from ..apps.batch_pir import (BatchPIROptimize, CollocateConfig,
                                  HotColdConfig, PIRConfig)
    rng = np.random.default_rng(seed)
    table = rng.integers(0, 2 ** 31, (entries, entry_size),
                         dtype=np.int64).astype(np.int32)
    cover = [list(range(i, min(i + 512, entries)))
             for i in range(0, entries, 512)]
    opt = BatchPIROptimize(
        cover, cover, HotColdConfig(1.0), CollocateConfig(0),
        PIRConfig(bin_fraction=bin_fraction, queries_to_hot=1))
    return table, opt


def _wanted_rounds(opt, entries, rounds, seed=1):
    """One needed-index batch per round (zipf-ish popularity)."""
    rng = np.random.default_rng(seed)
    pop = 1.0 / np.arange(1, entries + 1)
    pop /= pop.sum()
    want = max(1, len(opt.hot_table_bins) // 2)
    return [[int(x) for x in rng.choice(entries, size=want, p=pop)]
            for _ in range(rounds)]


def pir_point(entries=32768, entry_size=16, bin_fraction=1 / 256.,
              prf=None, scheme="logn", radix=2, rounds=6, reps=3,
              quiet=False):
    """Benchmark one batch-PIR deployment point; returns the point dict.

    Every timed candidate is equality-gated against the scalar oracles
    first: batched keys vs the per-bin gen loop (pinned seeds), the
    packed/tuned/async ``answer`` vs ``answer_scalar``, streaming
    results vs ``answer``, and the recovered rows vs the table itself.
    """
    from ..apps.batch_pir import PrivateLookupClient, PrivateLookupServer
    from ..core.prf_ref import PRF_CHACHA20, PRF_NAMES

    if prf is None:
        prf = PRF_CHACHA20          # a real cipher: the scalar per-bin
        #       gen loop pays Python-int PRF calls, the regime the
        #       vectorized keygen targets
    t0 = time.perf_counter()
    table, opt = _workload(entries, entry_size, bin_fraction)
    plan_s = time.perf_counter() - t0

    server_a = PrivateLookupServer(table, opt.hot_table_bins, prf=prf,
                                   radix=radix, scheme=scheme)
    server_b = PrivateLookupServer(table, opt.hot_table_bins, prf=prf,
                                   radix=radix, scheme=scheme)
    client = PrivateLookupClient(opt.hot_table_bins, server_a.bin_sizes,
                                 prf=prf, radix=radix, scheme=scheme,
                                 entry_size=entry_size)
    n_bins = len(server_a.bins)
    rounds_w = _wanted_rounds(opt, entries, rounds)

    # ---- equality gates (never timed) --------------------------------
    seeds = [b"bench-pir-%d" % i for i in range(n_bins)]
    ka, kb, plan = client.make_queries(rounds_w[0], seeds=seeds)
    ka_s, kb_s, plan_s2 = client.make_queries_scalar(rounds_w[0],
                                                     seeds=seeds)
    assert plan == plan_s2, "batched plan diverged from the scalar loop"
    for a, b in zip(ka + kb, ka_s + kb_s):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise AssertionError("batched keygen diverged from the "
                                 "per-bin gen loop")
    ans_a = server_a.answer(ka)
    if not np.array_equal(ans_a, server_a.answer_scalar(ka)):
        raise AssertionError("packed answer diverged from answer_scalar")
    got = client.recover(ans_a, server_b.answer(kb), plan)
    for w, row in got.items():
        if not np.array_equal(row, table[w]):
            raise AssertionError("recovered row %d mismatches the table"
                                 % w)

    # ---- keygen: batched vs per-bin loop -----------------------------
    best_b = best_s = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        client.make_queries(rounds_w[0])
        best_b = min(best_b, time.perf_counter() - t0)
        t0 = time.perf_counter()
        client.make_queries_scalar(rounds_w[0])
        best_s = min(best_s, time.perf_counter() - t0)
    keygen = {"bins": n_bins, "scalar_s": round(best_s, 6),
              "batched_s": round(best_b, 6),
              "speedup": round(best_s / best_b, 2)}

    # ---- answer: packed/tuned/async vs scalar/per-group-sync ---------
    best_n = best_s = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        server_a.answer(ka)
        best_n = min(best_n, time.perf_counter() - t0)
        t0 = time.perf_counter()
        server_a.answer_scalar(ka)
        best_s = min(best_s, time.perf_counter() - t0)
    answer = {"scalar_s": round(best_s, 6), "batched_s": round(best_n, 6),
              "speedup": round(best_s / best_n, 2),
              "size_groups": {str(n): len(g.idxs)
                              for n, g in server_a._groups.items()}}

    # ---- end-to-end: keygen -> answer x2 -> recover over all rounds --
    def e2e(batched: bool) -> float:
        t0 = time.perf_counter()
        for wanted in rounds_w:
            if batched:
                a, b, p = client.make_queries(wanted)
                client.recover(server_a.answer(a), server_b.answer(b), p)
            else:
                a, b, p = client.make_queries_scalar(wanted)
                client.recover(server_a.answer_scalar(a),
                               server_b.answer_scalar(b), p)
        return time.perf_counter() - t0

    e2e_new = min(e2e(True) for _ in range(max(1, reps - 1)))
    e2e_old = min(e2e(False) for _ in range(max(1, reps - 1)))
    total_q = n_bins * rounds

    # ---- streaming: LookupStream rounds vs sequential answer() -------
    st_a = server_a.stream(max_in_flight=2, warmup=True)
    st_b = server_b.stream(max_in_flight=2, warmup=True)
    key_rounds = [client.make_queries(w) for w in rounds_w]
    futs = [(st_a.submit(a), st_b.submit(b), p)
            for a, b, p in key_rounds]  # warm + gate pass
    st_a.drain(), st_b.drain()
    for (fa, fb, p), (a, b, _) in zip(futs, key_rounds):
        if not (np.array_equal(fa.result(), server_a.answer(a))
                and np.array_equal(fb.result(), server_b.answer(b))):
            raise AssertionError("streaming answers diverged from "
                                 "answer()")
    t0 = time.perf_counter()
    futs = [(st_a.submit(a), st_b.submit(b), p) for a, b, p in key_rounds]
    for fa, fb, p in futs:
        client.recover(fa.result(), fb.result(), p)
    stream_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for a, b, p in key_rounds:
        client.recover(server_a.answer(a), server_b.answer(b), p)
    seq_s = time.perf_counter() - t0

    point = {
        "entries": entries, "entry_size": entry_size,
        "bin_fraction": bin_fraction, "bins": n_bins,
        "rounds": rounds, "prf": PRF_NAMES[prf],
        "scheme": scheme, "radix": radix,
        "plan_s": round(plan_s, 4),
        "keygen": keygen,
        "answer": answer,
        "e2e": {"scalar_s": round(e2e_old, 4),
                "batched_s": round(e2e_new, 4),
                "speedup": round(e2e_old / e2e_new, 2),
                "batched_qps": int(total_q / e2e_new),
                "scalar_qps": int(total_q / e2e_old)},
        "streaming": {"stream_s": round(stream_s, 4),
                      "sequential_s": round(seq_s, 4),
                      "speedup": round(seq_s / stream_s, 2),
                      "qps": int(total_q / stream_s),
                      "stats": st_a.stats()},
        "group_constructions": {
            str(n): list(c)
            for n, c in server_a.group_constructions().items()},
    }
    if not quiet:
        print(json.dumps(point), flush=True)
    return point


DEFAULT_POINTS = (
    # 256 bins x 128 entries on the radix-4 construction: the >=256-bin
    # keygen regime where the vectorized generator replaces a pure-
    # Python per-bin loop (the binary scheme also has the native C++
    # generator, which gen_batched_binary already routes through)
    {"entries": 32768, "bin_fraction": 1 / 256., "radix": 4},
    # binary wire-compatible point with an uneven split -> two size
    # groups (512-entry bins + a remainder bin): exercises the
    # multi-group async dispatch
    {"entries": 4096, "bin_fraction": 0.1, "radix": 2},
)


def pir_bench(points=None, *, prf=None, scheme=None, radix=None,
              rounds=6, reps=3, out=None, quiet=False) -> dict:
    """``benchmark.py --batch-pir``: run every point, emit ONE JSON
    record (committed as ``BENCH_PIR_r09.json``), headline = the largest
    point's end-to-end throughput vs the pre-PR path.  Per-point dicts
    may pin ``scheme``/``radix`` (the defaults race the radix-4 and
    binary constructions); an EXPLICIT caller scheme/radix overrides
    the per-point pins wholesale."""
    override = {}
    if scheme is not None:
        override["scheme"] = scheme
        override["radix"] = 2 if scheme == "sqrtn" else (radix or 2)
    elif radix is not None:
        override["radix"] = radix
    pts = [pir_point(prf=prf, rounds=rounds, reps=reps, quiet=True,
                     **{"scheme": "logn", "radix": 2, **p, **override})
           for p in (points or DEFAULT_POINTS)]
    head = max(pts, key=lambda p: p["entries"])
    record = {
        "metric": "end-to-end batch-PIR (plan->keygen->answer->recover, "
                  "%d bins x %d rounds, entries=%d, %s, 1 device)"
                  % (head["bins"], head["rounds"], head["entries"],
                     head["prf"]),
        "value": head["e2e"]["batched_qps"],
        "unit": "bin-queries/sec",
        "vs_baseline": round(head["e2e"]["scalar_s"]
                             / head["e2e"]["batched_s"], 4),
        "baseline": "pre-PR batch-PIR path: per-bin DPF.gen loop + "
                    "per-key deserialize + heuristic knobs + per-group "
                    "host sync, identical plan and seeds",
        "points": pts,
        "checked": True,  # every timed candidate passed the scalar-
        #                   oracle equality gates first
    }
    from ..obs import record_sections
    record["obs"] = record_sections()
    if not quiet:
        print(json.dumps(record), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--entries", type=int, default=None,
                    help="single point: table entries (default: the "
                         "two-point default sweep)")
    ap.add_argument("--bin-fraction", type=float, default=1 / 256.)
    ap.add_argument("--prf", type=int, default=None,
                    help="PRF id (default 2=ChaCha20)")
    ap.add_argument("--scheme", default=None,
                    choices=("logn", "sqrtn", "auto"),
                    help="override every point's construction (default: "
                         "the per-point pins)")
    ap.add_argument("--radix", type=int, default=None, choices=(2, 4))
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    points = None
    if args.entries:
        points = [{"entries": args.entries,
                   "bin_fraction": args.bin_fraction}]
    return pir_bench(points, prf=args.prf, scheme=args.scheme,
                     radix=args.radix, rounds=args.rounds, reps=args.reps,
                     out=args.out)


if __name__ == "__main__":
    main()
