"""Multi-host chaos benchmark: availability across a host death.

``benchmark.py --multihost``.  Builds a serving CLUSTER over one table
(``parallel/cluster.py``: row-sharded granules, scatter/gather
front-end, re-shard-or-degrade recovery) and replays the same seeded
bursty trace three times:

* **baseline**       — full cluster, no failures: the availability
  reference for this machine/trace.
* **chaos_degrade**  — one host dies mid-trace; recovery policy
  ``degrade``: a front-end spare takes over the dead granules while
  the breaker keeps the dead host out of the scatter plan.
* **chaos_reshard**  — the same death; policy ``reshard``: the dead
  host's granules are redistributed over the survivors (device_put
  only — the traced-row0 program never recompiles).

A fourth section, ``pir_group_routing``, gates the batch-PIR
size-group routing tier (``parallel/cluster.ClusterPIRRouter``):
routed dispatch (each size group only to the hosts whose bins cover
it) must bit-match both the broadcast replay and the single-server
oracle while strictly reducing per-host size-group deliveries.

Two execution modes run the IDENTICAL router/recovery state machine:

* ``multiprocess`` (default) — one OS process per host
  (``parallel/cluster_worker.py`` over the framed-pickle socket
  transport); the chaos legs SIGKILL the victim worker at a fixed
  arrival index, so the loss is a *real* process death detected
  through the transport (``HostUnreachable``), not a simulated flag.
  This forced-multiprocess CPU rehearsal runs on any jax — the workers
  are independent single-process jax runtimes; cross-process
  *collectives* (``utils.compat.has_cpu_multiprocess``, jax >= 0.5)
  are not required and the record says which story it proves.
* ``simulated`` — all hosts in-process; the death is an injected
  ``host_drop`` fault (``serve/faults.py``, deterministic under the
  plan seed).  The CI smoke fallback and the tier-1 test path.

**Availability** is the correct-within-SLO fraction: every merged
answer is bit-gated against the scalar oracle (``DPF.eval_cpu``)
before the client accepts it, failed gates re-serve through
``ClusterRouter.submit_resilient``, and the record proves the drop was
*attributed*: the flight recorder must contain the ``host_drop`` event
and the ``cluster_recovery`` decision that answered it, per leg.
Committed record: ``MULTIHOST_r14.json``; the identical command
produces the relay-pod record.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --multihost [--dryrun] [--simulate] \
      [--hosts H] [--out FILE]
"""

from __future__ import annotations

import json
import sys

import numpy as np

from ..core import expand
from ..core.expand import DeadlineExceeded
from ..obs import FLIGHT, flight_dump, record_sections
from ..utils.profiling import note_swallowed, swallowed_snapshot
from .bench_load import _batch_for, _key_pool, _slo_stats, replay
from .engine import LoadShed
from .faults import FaultPlan, FaultSpec
from . import loadgen


class _FailedBatch:
    """Future-shaped sentinel for an arrival whose serve attempts were
    exhausted (counts unavailable in the availability fraction)."""
    ok = False

    def done(self) -> bool:
        return True

    def result(self):
        return None


class _VerifiedFuture:
    """The full client protocol for one scattered batch: resolve the
    merged share, bit-gate it against the scalar-oracle references,
    and on a failed gate or a resolve-time fault RE-SERVE through
    ``submit_resilient`` (the re-serve cost lands in the measured
    latency, so recovery is paid for inside the availability number)."""

    __slots__ = ("client", "a", "j", "fut", "ok", "_value")

    def __init__(self, client, a, j, fut):
        self.client = client
        self.a = a
        self.j = j
        self.fut = fut
        self.ok = None
        self._value = None

    def done(self) -> bool:
        return self.ok is not None or self.fut.done()

    def result(self):
        if self.ok is not None:
            return self._value
        c = self.client
        out = None
        for attempt in range(c.max_reserves + 1):
            try:
                out = np.asarray(self.fut.result())
            except (LoadShed, DeadlineExceeded):
                raise
            except Exception:
                out = None
            if out is not None:
                if np.array_equal(out, c.refs_for(self.j, self.a.batch)):
                    self.ok = True
                    self._value = out
                    return out
                c.detected_corruptions += 1
            if attempt >= c.max_reserves:
                break
            c.reserves += 1
            try:
                self.fut = c.cluster.submit_resilient(
                    c.keys_for(self.j, self.a.batch))
            except Exception:
                break
        self.ok = False
        self._value = out
        c.failed_batches += 1
        return out


class _ClusterClient:
    """The submit side of one leg: heartbeat sweep every
    ``hb_every`` arrivals (host loss is detectable BETWEEN dispatches),
    the multiprocess kill switch at the scripted arrival, then
    ``submit_resilient`` wrapped in the verify-and-reserve protocol."""

    def __init__(self, cluster, pool, injector, *, max_reserves=3,
                 hb_every=8, kill_at=None, victim_node=None):
        self.cluster = cluster
        self.pool = pool
        self.injector = injector
        self.max_reserves = max_reserves
        self.hb_every = hb_every
        self.kill_at = kill_at
        self.victim_node = victim_node      # RemoteHost to SIGKILL
        self.killed = False
        self.detected_corruptions = 0
        self.failed_batches = 0
        self.reserves = 0

    def keys_for(self, j, b):
        return _batch_for(self.pool, j, b)[0]

    def refs_for(self, j, b):
        _, idxs = _batch_for(self.pool, j, b)
        return self.pool[1][idxs]

    def submit(self, a, j):
        if self.injector is not None:
            self.injector.begin_arrival(j)
        if (self.victim_node is not None and not self.killed
                and self.kill_at is not None and j >= self.kill_at):
            self.victim_node.kill()         # a REAL process death
            self.killed = True
        if self.hb_every and j and j % self.hb_every == 0:
            self.cluster.check_hosts()
        try:
            fut = self.cluster.submit_resilient(
                self.keys_for(j, a.batch))
        except (LoadShed, DeadlineExceeded):
            raise
        except Exception:
            self.failed_batches += 1
            return _FailedBatch()
        return _VerifiedFuture(self, a, j, fut)


def _pir_routing_leg(*, prf, hosts, seed, dryrun=False) -> dict:
    """Batch-PIR size-group routing leg (PR-11 remainder): a
    bin-sharded ``ClusterPIRRouter`` answers one query round twice —
    ``routed`` (each size group dispatched only to its owner hosts)
    and ``broadcast`` (every group to every host, the pre-routing
    behaviour) — and both are bit-gated against the single-server
    oracle AND against each other; the record proves routing strictly
    reduces per-host size-group deliveries without changing a bit of
    the merged answers."""
    from ..apps.batch_pir import PrivateLookupClient, PrivateLookupServer
    from ..parallel.cluster import ClusterPIRRouter

    rng = np.random.default_rng(seed ^ 0x91A)
    if dryrun:
        n_pir, e = 1024, 4
        sizes = (150, 130, 60, 50, 20, 10)
    else:
        n_pir, e = 4096, 8
        sizes = (700, 650, 300, 260, 130, 120, 60, 50)
    table = rng.integers(0, 2**31, size=(n_pir, e), dtype=np.int32)
    universe = rng.permutation(n_pir)
    bins, off = [], 0
    for sz in sizes:
        bins.append(universe[off:off + sz].tolist())
        off += sz
    pir_hosts = max(2, min(hosts, 4))

    oracle_a = PrivateLookupServer(table, bins, prf=prf, scheme="logn")
    oracle_b = PrivateLookupServer(table, bins, prf=prf, scheme="logn")
    client = PrivateLookupClient(bins, oracle_a.bin_sizes, prf=prf,
                                 scheme="logn")
    wanted = [b[len(b) // 2] for b in bins]
    ka, kb, plan = client.make_queries(wanted)

    routed = ClusterPIRRouter(table, bins, hosts=pir_hosts, prf=prf,
                              scheme="logn", routed=True)
    bcast = ClusterPIRRouter(table, bins, hosts=pir_hosts, prf=prf,
                             scheme="logn", routed=False)
    ans_oracle = np.asarray(oracle_a.answer(ka))
    ans_routed = routed.answer(ka)
    ans_bcast = bcast.answer(ka)
    parity = bool(np.array_equal(ans_routed, ans_oracle)
                  and np.array_equal(ans_bcast, ans_oracle))
    rec = client.recover(ans_routed, np.asarray(oracle_b.answer(kb)),
                         plan)
    recover_ok = all(np.array_equal(rec[t], table[t]) for t in wanted)
    r_total = sum(routed.dispatch_counts.values())
    b_total = sum(bcast.dispatch_counts.values())
    return {
        "hosts": pir_hosts,
        "bins": len(bins),
        "bin_sizes": list(sizes),
        "group_sizes": list(routed.group_sizes),
        "owners": {int(n): lbs for n, lbs in routed.owners.items()},
        "bins_per_host": routed.stats()["bins_per_host"],
        "routed_dispatches": r_total,
        "broadcast_dispatches": b_total,
        "dispatch_counts_routed": dict(routed.dispatch_counts),
        "dispatch_counts_broadcast": dict(bcast.dispatch_counts),
        "dispatch_reduction": (round(1 - r_total / b_total, 4)
                               if b_total else None),
        "parity_vs_oracle": parity,
        "recover_ok": recover_ok,
        "checked": bool(parity and recover_ok and r_total < b_total),
    }


def _build_cluster(mode, table, hosts, *, oracle, buckets, policy,
                   injector, breaker_reset_s, table_seed):
    """A fresh cluster for one leg.  Returns (cluster, victim_node) —
    victim_node is the RemoteHost the chaos legs kill (None in
    simulated mode, where the injector supplies the death)."""
    from ..parallel.cluster import ClusterRouter

    if mode == "multiprocess":
        from ..parallel import cluster_net
        n, e = table.shape
        nodes = cluster_net.spawn_cluster(
            n, e, hosts, table_seed=table_seed,
            prf_method=oracle.prf_method, buckets=buckets)
        cluster = ClusterRouter(
            nodes, granule=n // hosts,
            table_perm=expand.permute_table(table), policy=policy,
            prf_method=oracle.prf_method,
            breaker_reset_s=breaker_reset_s,
            spare_engine_kw={"buckets": buckets},
            # the front-end never served, so its jit caches are cold:
            # warm the degrade spare BEFORE the chaos window, making
            # failover a device_put swap instead of a compile stall
            standby=True)
        return cluster, dict(zip([nd.label for nd in nodes], nodes))
    cluster = ClusterRouter.local(
        table, hosts=hosts, oracle=oracle, buckets=buckets,
        injector=injector, policy=policy,
        breaker_reset_s=breaker_reset_s)
    return cluster, None


def _run_leg(mode, table, hosts, trace, pool, oracle, *, buckets,
             policy, slo_s, window, seed, victim=None, kill_at=None,
             breaker_reset_s=0.4, table_seed=0) -> dict:
    """One replay of ``trace`` through a fresh cluster; chaos legs
    (victim set) lose that host at ``kill_at`` — by SIGKILL in
    multiprocess mode, by injected ``host_drop`` in simulated mode."""
    injector = None
    if mode == "simulated":
        specs = []
        if victim is not None:
            specs.append(FaultSpec(kind="host_drop", construction=victim,
                                   start=kill_at))
        injector = FaultPlan(specs, seed=seed).injector()
    seq0 = FLIGHT.recorded
    cluster, nodes = _build_cluster(
        mode, table, hosts, oracle=oracle, buckets=buckets,
        policy=policy, injector=injector,
        breaker_reset_s=breaker_reset_s, table_seed=table_seed)
    victim_node = nodes.get(victim) if (nodes and victim) else None
    try:
        cluster.warmup()
        client = _ClusterClient(cluster, pool, injector,
                                kill_at=kill_at if victim else None,
                                victim_node=victim_node)
        lats, done, makespan, _, _ = replay(trace, client.submit,
                                            window=window)
        cluster.drain()

        ok_in_slo = sum(1 for (_, _, fut), lat in zip(done, lats)
                        if getattr(fut, "ok", False) and lat <= slo_s)
        escapes = 0
        for a, j, fut in done:  # re-gate final values: escapes must be 0
            if not getattr(fut, "ok", False):
                continue
            if not np.array_equal(fut.result(),
                                  client.refs_for(j, a.batch)):
                escapes += 1
        counters = cluster.counters()
        # the attribution chain: THIS leg's flight events must contain
        # the host_drop and the recovery decision that answered it
        leg_events = [ev for ev in flight_dump()
                      if ev["seq"] > seq0
                      and ev["kind"] in ("host_drop", "cluster_recovery")]
        drops = [ev for ev in leg_events if ev["kind"] == "host_drop"]
        recoveries = [ev for ev in leg_events
                      if ev["kind"] == "cluster_recovery"
                      and ev.get("ok")]
        attributed = bool(
            victim is None
            or (any(ev.get("host") == victim for ev in drops)
                and any(ev.get("host") == victim
                        and ev.get("decision") == policy
                        for ev in recoveries)))
        total = len(trace)
        rec = {
            "mode": mode,
            "policy": policy,
            "availability": (round(ok_in_slo / total, 4)
                             if total else None),
            "served_ok": ok_in_slo,
            "arrivals": total,
            "failed_batches": client.failed_batches,
            "reserves_after_gate": client.reserves,
            "makespan_s": round(makespan, 4),
            "qps": (int(loadgen.total_queries(trace) / makespan)
                    if makespan else None),
            **_slo_stats(lats, slo_s),
            "recovery": {
                "retries": counters.retries,
                "failovers": counters.failovers,
                "breaker_opens": counters.breaker_opens,
                "engine_restarts": counters.engine_restarts,
                "swallowed_errors": counters.swallowed_errors,
            },
            "decision_counts": dict(cluster.decision_counts),
            "host_states": {lb: cluster.host_state(lb)
                            for lb in cluster.hosts},
            "assignment": {lb: list(g)
                           for lb, g in cluster.assignment.items()},
            "gate_escapes": escapes,
            "drop_attributed": attributed,
            "flight_events": leg_events,
        }
        if victim is not None:
            rec["victim"] = victim
            rec["killed_at_arrival"] = kill_at
        if injector is not None:
            rec["faults"] = {
                "plan": FaultPlan(injector.plan.specs,
                                  seed=injector.plan.seed).as_dict(),
                "injected": dict(injector.injected),
            }
        return rec
    finally:
        cluster.close()
        if nodes:
            for node in nodes.values():
                try:
                    node.kill()
                except Exception as e:
                    note_swallowed("cluster.peer_unreachable", e)


def multihost_bench(n=4096, entry_size=16, cap=128, prf=0, *,
                    hosts=4, mode="multiprocess", seed=14,
                    duration_s=6.0, on_rate=60.0, slo_ms=1000.0,
                    window=8, distinct=16, breaker_reset_s=0.4,
                    quiet=False) -> dict:
    """Baseline + host-death chaos legs over one seeded bursty trace;
    returns the ``--multihost`` record (``MULTIHOST_r14.json``)."""
    from ..api import DPF
    from ..parallel import cluster_net
    from ..utils.compat import has_cpu_multiprocess
    from .buckets import Buckets

    FLIGHT.clear()      # scope the embedded flight events to this bench
    table_seed = seed ^ 0x5107
    table = cluster_net.make_table(n, entry_size, table_seed)
    oracle = DPF(prf=prf)
    oracle.eval_init(table)
    trace = loadgen.bursty_trace(
        on_rate=on_rate, off_rate=2.0, on_s=1.0, off_s=2.0,
        duration_s=duration_s, cap=cap, seed=seed, n=n)
    slo_s = slo_ms / 1e3
    buckets = Buckets.default_sizes(cap)
    pool = _key_pool(oracle, n, distinct, b"multihost")
    victim = "host%d" % (hosts - 1)
    kill_at = max(1, len(trace) // 3)

    if mode == "multiprocess":
        # prove the transport is viable before committing three legs to
        # it; an unspawnable worker (sandbox, no sockets) degrades to
        # the simulated tier with the cause on the record
        try:
            probe = cluster_net.spawn_cluster(
                n, entry_size, 1, table_seed=table_seed,
                prf_method=oracle.prf_method, buckets=buckets,
                timeout_s=120.0)
            for node in probe:
                node.close()
        except Exception as e:
            note_swallowed("cluster.peer_unreachable", e)
            mode = "simulated"

    leg_kw = dict(buckets=buckets, slo_s=slo_s, window=window,
                  seed=seed, breaker_reset_s=breaker_reset_s,
                  table_seed=table_seed)
    baseline = _run_leg(mode, table, hosts, trace, pool, oracle,
                        policy="reshard", **leg_kw)
    degrade_leg = _run_leg(mode, table, hosts, trace, pool, oracle,
                           policy="degrade", victim=victim,
                           kill_at=kill_at, **leg_kw)
    reshard_leg = _run_leg(mode, table, hosts, trace, pool, oracle,
                           policy="reshard", victim=victim,
                           kill_at=kill_at, **leg_kw)
    pir_leg = _pir_routing_leg(prf=prf, hosts=hosts, seed=seed,
                               dryrun=n <= 1024)

    chaos_avail = [leg["availability"]
                   for leg in (degrade_leg, reshard_leg)]
    total_escapes = (baseline["gate_escapes"]
                     + degrade_leg["gate_escapes"]
                     + reshard_leg["gate_escapes"])
    record = {
        "metric": "multi-host serving cluster: availability (correct-"
                  "within-SLO fraction) across a host death — %d hosts "
                  "over one [%d x %d] table (prf=%d), one host lost at "
                  "arrival %d/%d, recovery by degrade (front-end spare) "
                  "and by re-shard over survivors (mode=%s; every "
                  "merged answer bit-gated against the scalar oracle)"
                  % (hosts, n, entry_size, prf, kill_at, len(trace),
                     mode),
        "value": min(chaos_avail) if all(
            a is not None for a in chaos_avail) else None,
        "unit": "availability",
        "vs_baseline": (round(min(chaos_avail)
                              / baseline["availability"], 4)
                        if baseline["availability"]
                        and all(a is not None for a in chaos_avail)
                        else None),
        "baseline": "the identical cluster replaying the identical "
                    "seeded trace with no host loss",
        "mode": mode,
        "hosts": hosts,
        "has_cpu_multiprocess": has_cpu_multiprocess(),
        "slo_ms": slo_ms,
        "trace": {"kind": "bursty", "seed": seed,
                  "duration_s": duration_s, "on_rate": on_rate,
                  "arrivals": len(trace),
                  "queries": loadgen.total_queries(trace),
                  "cap": cap, "window": window},
        "victim": victim,
        "killed_at_arrival": kill_at,
        "baseline_leg": baseline,
        "chaos_degrade_leg": degrade_leg,
        "chaos_reshard_leg": reshard_leg,
        "pir_group_routing": pir_leg,
        "swallowed_errors": swallowed_snapshot(),
        "gate_escapes": total_escapes,
        "checked": bool(
            total_escapes == 0
            and all(a is not None and a >= 0.95 for a in chaos_avail)
            and degrade_leg["drop_attributed"]
            and reshard_leg["drop_attributed"]
            and degrade_leg["decision_counts"]["degrade"] >= 1
            and reshard_leg["decision_counts"]["reshard"] >= 1
            and pir_leg["checked"]),
    }
    record["obs"] = record_sections()
    if not record["checked"]:
        # a failed gate is exactly what the flight recorder exists to
        # diagnose: embed the FULL ring (scatter plans, the host_drop,
        # the recovery decision, every fault with its arrival join key)
        record["obs"]["flight_on_gate_failure"] = flight_dump()
        print("multihost gate FAILED — full flight dump embedded in "
              "record (obs.flight_on_gate_failure, %d events)"
              % len(record["obs"]["flight_on_gate_failure"]),
              file=sys.stderr, flush=True)
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--cap", type=int, default=128)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--hosts", type=int, default=4,
                    help="serving hosts (power of two dividing n)")
    ap.add_argument("--seed", type=int, default=14)
    ap.add_argument("--duration", type=float, default=6.0,
                    help="trace duration in seconds")
    ap.add_argument("--on-rate", type=float, default=60.0,
                    help="burst arrival rate (arrivals/sec in ON "
                         "windows)")
    ap.add_argument("--slo-ms", type=float, default=1000.0)
    ap.add_argument("--simulate", action="store_true",
                    help="force the in-process simulation tier")
    ap.add_argument("--multiprocess", action="store_true",
                    help="force one OS process per host (default; "
                         "falls back to --simulate when workers can't "
                         "spawn)")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): exercises every "
                         "leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.simulate and args.multiprocess:
        ap.error("--simulate and --multiprocess are mutually exclusive")
    mode = "simulated" if args.simulate else "multiprocess"
    if args.dryrun:
        record = multihost_bench(n=512, entry_size=8, cap=16,
                                 prf=args.prf, hosts=min(args.hosts, 4),
                                 mode=mode, seed=args.seed,
                                 duration_s=1.5, on_rate=20.0,
                                 slo_ms=args.slo_ms, distinct=8,
                                 breaker_reset_s=0.2)
    else:
        record = multihost_bench(n=args.n, entry_size=args.entry_size,
                                 cap=args.cap, prf=args.prf,
                                 hosts=args.hosts, mode=mode,
                                 seed=args.seed,
                                 duration_s=args.duration,
                                 on_rate=args.on_rate,
                                 slo_ms=args.slo_ms)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
