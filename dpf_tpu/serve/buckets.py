"""Shape-bucketed batching: bound the number of compiled XLA programs.

Every distinct batch size is a distinct jitted program shape; a ragged
query stream would compile one program per size it happens to produce.
The engine instead pads each incoming batch up to the smallest member of
a small fixed set of power-of-two bucket sizes, so at most
``len(sizes)`` programs ever compile per (table, PRF, kernel) config —
and all of them can be precompiled at init (``ServingEngine.warmup``).

The tradeoff is pad waste: with the default /2 ladder (64/128/256/512
for a 512 cap) a batch lands at most 2x above its real size, and the
pad rows are discarded after the dispatch.  A sparser /4 ladder halves
the compile count at double the worst-case waste — see docs/SERVING.md.
"""

from __future__ import annotations


class Buckets:
    """A sorted set of power-of-two batch-shape buckets."""

    def __init__(self, sizes):
        sizes = sorted({int(s) for s in sizes})
        if not sizes:
            raise ValueError("need at least one bucket size")
        for s in sizes:
            if s < 1 or (s & (s - 1)) != 0:
                raise ValueError(
                    "bucket sizes must be powers of two >= 1 (got %r)"
                    % (s,))
        self.sizes = tuple(sizes)
        self.max = sizes[-1]

    @staticmethod
    def default_sizes(cap: int, fanout: int = 2, count: int = 4) -> tuple:
        """A geometric ladder below ``cap``: cap, cap/fanout, ... (pow2;
        a non-pow2 cap rounds down).  cap=512 -> (64, 128, 256, 512):
        pad waste < 2x at any size above the smallest bucket.  Pad rows
        are fully evaluated, so waste is device time — prefer a ladder
        whose rungs straddle the real batch-size distribution."""
        s = 1
        while s * 2 <= max(1, cap):
            s *= 2
        out = []
        while s >= 1 and len(out) < count:
            out.append(s)
            s //= fanout
        return tuple(reversed(out))

    @staticmethod
    def ladder_candidates(cap: int) -> list:
        """The autotuner's ladder search space (tune/serve_tune.py):
        the default /2x4 ladder, a sparser /4x2, a two-rung /2, and the
        single-bucket ladder — spanning the compile-count vs pad-waste
        tradeoff.  Deduplicated, order preserved."""
        cands = [
            Buckets.default_sizes(cap, fanout=2, count=4),
            Buckets.default_sizes(cap, fanout=4, count=2),
            Buckets.default_sizes(cap, fanout=2, count=2),
            Buckets.default_sizes(cap, fanout=2, count=1),
        ]
        out = []
        for c in cands:
            if c not in out:
                out.append(c)
        return out

    def bucket_for(self, b: int) -> int:
        """Smallest bucket >= b (b must be in (0, max])."""
        if b < 1:
            raise ValueError("batch must be >= 1 (got %d)" % b)
        for s in self.sizes:
            if s >= b:
                return s
        raise ValueError("batch %d exceeds the largest bucket %d "
                         "(split with chunks())" % (b, self.max))

    def chunks(self, b: int) -> list:
        """Split a batch of ``b`` keys into (lo, hi) spans, each at most
        one max bucket wide: full max-sized spans then one remainder."""
        if b < 1:
            raise ValueError("batch must be >= 1 (got %d)" % b)
        spans = []
        lo = 0
        while b - lo > self.max:
            spans.append((lo, lo + self.max))
            lo += self.max
        spans.append((lo, b))
        return spans
