"""Billion-row table tier benchmark: 2D sharding + granule HBM paging.

``benchmark.py --bigtable``.  Four legs, one story: a table LARGER
than any single device budget served end-to-end, bit-identical to the
single-host oracle, with the paging cost pushed off the critical path.

* **paged_cluster** — a serving cluster whose hosts are each ASSIGNED
  more table bytes than their device budget holds
  (``ClusterShardServer(budget_bytes=...)`` over a
  ``serve.registry.GranuleStore``): granules demand-page on dispatch,
  evict LRU-first under budget pressure, and every merged answer is
  bit-gated against the scalar oracle (``DPF.eval_cpu``) — the
  end-to-end proof that paged residency never changes a bit.
* **prefetch_race** — the same paged host serving the same seeded
  trace twice under periodic residency pressure (``demote_all``
  between arrivals — registry-level pressure from other tenants,
  identical in both legs): ``prefetch_off`` demand-pages inside the
  measured dispatch window; ``prefetch_on`` re-promotes in
  ``GranulePrefetcher.tick()`` BETWEEN arrivals, sized by the trace's
  per-bucket arrival rates (``loadgen.bucket_rates`` — the offline
  twin of ``SchemeRouter.arrival_rates``).  Gate: prefetch-on p99 must
  not lose.
* **mesh_2d** — the 2D row x entry-byte mesh programs
  (``sharded.eval_sharded_2d``) on the forced 8-device CPU mesh:
  every (batch, table, byte) split x psum_group variant must bit-match
  BOTH the 1D row-sharded path and the single-chip oracle (per-chip
  bytes shrink by n_table x n_byte — the sharding that spreads one
  big table over the whole grid).
* **plan** — HBM as a first-class planning resource:
  ``plan.capacity.plan_fleet(table_bytes=...)`` answers "how many
  hosts for a 10^9-row table at this qps" with a jointly-monotone
  (load x table bytes) curve whose memory floor binds, and the twin's
  ``FleetConfig`` paging fields make under-budgeted replicas pay
  their stall in the fidelity legs.

Committed record: ``BIGTABLE_r19.json``.

  env JAX_PLATFORMS=cpu python benchmark.py --bigtable \
      [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

from ..core import expand
from ..obs import FLIGHT, flight_dump, record_sections
from ..utils.profiling import quantile, swallowed_snapshot
from .bench_load import _batch_for, _key_pool, _slo_stats, replay
from . import loadgen


# ------------------------------------------------- paged cluster leg


def _paged_cluster_leg(table, *, hosts, granules_per_host,
                       budget_granules, oracle, buckets, trace, pool,
                       slo_s, window) -> dict:
    """End-to-end paged serving: every host assigned
    ``granules_per_host`` granules with device budget for only
    ``budget_granules`` of them — dispatches walk the assignment
    leasing/evicting through the ``GranuleStore`` while the client
    bit-gates every merged answer against the scalar oracle."""
    from ..parallel.cluster import (ClusterRouter, ClusterShardServer,
                                    LocalHost)
    from .bench_multihost import _ClusterClient

    n, e = table.shape
    g = n // (hosts * granules_per_host)
    perm = expand.permute_table(table)
    granule_bytes = g * e * 4
    budget = budget_granules * granule_bytes
    nodes = []
    for i in range(hosts):
        row0s = tuple(range(i * granules_per_host * g,
                            (i + 1) * granules_per_host * g, g))
        srv = ClusterShardServer(perm, row0s, g,
                                 prf_method=oracle.prf_method,
                                 budget_bytes=budget)
        nodes.append(LocalHost("host%d" % i, srv, process_index=i,
                               buckets=buckets))
    cluster = ClusterRouter(nodes, granule=g, table_perm=perm,
                            policy="reshard")
    try:
        cluster.warmup()
        client = _ClusterClient(cluster, pool, injector=None)
        lats, done, makespan, _, _ = replay(trace, client.submit,
                                            window=window)
        cluster.drain()
        served_ok = sum(1 for (_, _, fut), lat in zip(done, lats)
                        if getattr(fut, "ok", False) and lat <= slo_s)
        escapes = 0
        for a, j, fut in done:  # re-gate final values: escapes must be 0
            if not getattr(fut, "ok", False):
                continue
            if not np.array_equal(fut.result(),
                                  client.refs_for(j, a.batch)):
                escapes += 1
        stores = {nd.label: nd.server.store.stats() for nd in nodes}
        assigned_bytes = granules_per_host * granule_bytes
        over_budget = all(assigned_bytes > st["budget_bytes"]
                          for st in stores.values())
        paged = all(st["counters"]["misses"] > 0
                    and st["counters"]["evictions"] > 0
                    for st in stores.values())
        total = len(trace)
        return {
            "hosts": hosts,
            "granule_rows": g,
            "granules_per_host": granules_per_host,
            "budget_granules": budget_granules,
            "assigned_bytes_per_host": assigned_bytes,
            "budget_bytes_per_host": budget,
            "assignment_exceeds_budget": over_budget,
            "availability": round(served_ok / total, 4) if total else None,
            "served_ok": served_ok,
            "arrivals": total,
            "failed_batches": client.failed_batches,
            "reserves_after_gate": client.reserves,
            "makespan_s": round(makespan, 4),
            **_slo_stats(lats, slo_s),
            "stores": stores,
            "gate_escapes": escapes,
            "checked": bool(over_budget and paged and escapes == 0
                            and client.failed_batches == 0
                            and served_ok == total),
        }
    finally:
        cluster.close()


# ------------------------------------------------- prefetch race leg


def _race_side(srv, trace, pool, *, prefetcher, pressure_every) -> dict:
    """One side of the prefetch race: serve ``trace`` sequentially
    through a paged shard server, timing each dispatch; residency
    pressure (``demote_all``) lands between arrivals, identically in
    both sides.  With ``prefetcher`` the untimed between-arrivals tick
    re-promotes what pressure evicted; without it the next TIMED
    dispatch demand-pages the cold granules."""
    keys0, refs = pool
    store = srv.store
    # warm the jit programs untimed — one dispatch per batch shape the
    # trace will offer, so the measured windows hold paging, not
    # compiles — then reset to the cold-start both sides race from
    for b in sorted({a.batch for a in trace}):
        pk = srv._decode_batch(_batch_for(pool, 0, b)[0])
        np.asarray(srv._dispatch_packed(pk))
    store.demote_all()
    lats, rejections = [], 0
    for j, a in enumerate(trace):
        kb, idxs = _batch_for(pool, j, a.batch)
        pk = srv._decode_batch(kb)
        t0 = time.perf_counter()
        out = np.asarray(srv._dispatch_packed(pk))
        lats.append(time.perf_counter() - t0)
        if not np.array_equal(out, refs[idxs]):
            rejections += 1
        if (j + 1) % pressure_every == 0:
            store.demote_all()          # registry pressure, both sides
        if prefetcher is not None:
            prefetcher.tick()           # untimed: between arrivals
    ms = sorted(x * 1e3 for x in lats)
    out = {
        "arrivals": len(trace),
        "pressure_every": pressure_every,
        "p50_ms": round(quantile(ms, 0.50, presorted=True), 3),
        "p99_ms": round(quantile(ms, 0.99, presorted=True), 3),
        "max_ms": round(ms[-1], 3),
        "gate_rejections": rejections,
        "store": store.stats(),
    }
    if prefetcher is not None:
        out["prefetcher"] = prefetcher.stats()
    return out


def _prefetch_race_leg(table, *, oracle, pool, trace, ladder,
                       granules) -> dict:
    """prefetch-on vs prefetch-off p99 under identical periodic
    residency pressure.  The ON side's tick budget is driven by the
    trace's own per-bucket arrival rates (``loadgen.bucket_rates``,
    the offline stand-in for ``SchemeRouter.arrival_rates``)."""
    from ..parallel.cluster import ClusterShardServer
    from .registry import GranulePrefetcher

    n, e = table.shape
    g = n // granules
    perm = expand.permute_table(table)
    budget = granules * g * e * 4        # full table fits: pressure,
    pressure_every = max(2, len(trace) // 6)  # not capacity, evicts
    rates = loadgen.bucket_rates(trace, ladder)

    def build():
        return ClusterShardServer(perm, tuple(range(0, n, g)), g,
                                  prf_method=oracle.prf_method,
                                  budget_bytes=budget)

    srv_off = build()
    off = _race_side(srv_off, trace, pool, prefetcher=None,
                     pressure_every=pressure_every)
    srv_on = build()
    on = _race_side(srv_on, trace, pool,
                    prefetcher=GranulePrefetcher(
                        srv_on.store, rates_fn=lambda: rates,
                        max_per_tick=granules),
                    pressure_every=pressure_every)
    return {
        "granules": granules,
        "granule_rows": g,
        "trace_bucket_rates_hz": {"%d" % bk: round(hz, 3)
                                  for bk, hz in rates.items()},
        "prefetch_off": off,
        "prefetch_on": on,
        "p99_speedup": (round(off["p99_ms"] / on["p99_ms"], 3)
                        if on["p99_ms"] else None),
        "checked": bool(
            on["p99_ms"] <= off["p99_ms"]
            and on["gate_rejections"] == 0
            and off["gate_rejections"] == 0
            and on["store"]["counters"]["prefetch_hits"] > 0),
    }


# ------------------------------------------------------- 2D mesh leg


def _mesh2d_leg(*, prf, seed, dryrun) -> dict:
    """Every (batch, table, byte) split x psum_group variant of the 2D
    mesh program, bit-gated against BOTH the 1D row-sharded path and
    the single-chip oracle (plus share-pair recovery of the exact
    table rows)."""
    from ..api import DPF
    from ..parallel import sharded
    from ..tune.fingerprint import mesh_tag

    n = 512 if dryrun else 2048
    e, batch = 8, 8
    rng = np.random.default_rng(seed ^ 0xB16)
    table = rng.integers(-2 ** 31, 2 ** 31, size=(n, e),
                         dtype=np.int64).astype(np.int32)
    dpf = DPF(prf=prf)
    keys = [dpf.gen((i * 997) % n, n) for i in range(batch)]
    idxs = [(i * 997) % n for i in range(batch)]
    k0s = [k[0] for k in keys]
    dpf.eval_init(table)
    single = np.asarray(dpf.eval_tpu(k0s))

    mesh1 = sharded.make_mesh(n_table=8, n_batch=1)
    one_d = np.asarray(sharded.ShardedDPFServer(
        table, mesh1, prf_method=prf, batch_size=batch).eval(k0s))

    variants = []
    for nb, nt, nby in ((1, 4, 2), (1, 2, 4), (2, 2, 2)):
        for pg in (0, 2):
            mesh = sharded.make_mesh_2d(n_table=nt, n_byte=nby,
                                        n_batch=nb)
            srv = sharded.ShardedDPFServer(table, mesh, prf_method=prf,
                                           batch_size=batch,
                                           psum_group=pg)
            a = np.asarray(srv.eval(k0s))
            b = np.asarray(srv.eval([k[1] for k in keys]))
            rec = (a.astype(np.int64) - b).astype(np.int32)
            variants.append({
                "mesh": mesh_tag(mesh),
                "psum_group": pg,
                "block_shape": [n // nt, e // nby],
                "parity_vs_single": bool(np.array_equal(a, single)),
                "parity_vs_1d": bool(np.array_equal(a, one_d)),
                "recover_ok": bool((rec == table[idxs]).all()),
            })
    return {
        "n": n, "entry_size": e, "batch": batch, "prf": prf,
        "parity_1d_vs_single": bool(np.array_equal(one_d, single)),
        "variants": variants,
        "checked": bool(
            np.array_equal(one_d, single)
            and all(v["parity_vs_single"] and v["parity_vs_1d"]
                    and v["recover_ok"] for v in variants)),
    }


# ----------------------------------------------------- planning leg


def _plan_leg() -> dict:
    """Memory-aware capacity planning at billion-row scale (pure
    stdlib — the cost table is a stated model, the gates are on the
    RELATIVE properties: the memory floor binds, the (load x table
    bytes) curve is jointly monotone, and the twin charges
    under-budgeted replicas their paging stall)."""
    from ..plan.capacity import min_hosts_for_memory, plan_fleet
    from ..plan.twin import CostTable, FleetConfig, simulate

    ct = CostTable({("logn", 64): 0.002, ("logn", 128): 0.0035,
                    ("logn", 256): 0.006, ("logn", 512): 0.011},
                   overhead_s=0.0005)
    trace = [(i * 0.01, 64) for i in range(200)]
    rows, e = 10 ** 9, 64                   # 1e9 rows x 64 int32 words
    table_bytes = rows * e * 4              # 256 GB: memory-bound
    hbm = 16 << 30
    plan = plan_fleet(trace, ct, label="logn", slo_s=0.05,
                      table_bytes=table_bytes, hbm_bytes_per_host=hbm)
    plan2 = plan_fleet(trace, ct, label="logn", slo_s=0.05,
                       table_bytes=2 * table_bytes,
                       hbm_bytes_per_host=hbm)
    floor = min_hosts_for_memory(table_bytes, hbm)
    memory_bound = all(c["hosts"] >= floor > c["hosts_throughput"]
                       for c in plan["headroom_curve"])
    jointly_monotone = bool(plan["monotone"] and plan2["monotone"]
                            and plan2["hosts"] >= plan["hosts"])

    base = dict(replicas={"logn": 2}, dispatch_blocking=False)
    f_none = FleetConfig(**base)
    f_page = FleetConfig(**base, table_bytes=8 << 30,
                         hbm_bytes_per_replica=4 << 30,
                         page_gbps=1024.0)
    f_over = FleetConfig(**base, table_bytes=8 << 30,
                         hbm_bytes_per_replica=4 << 30,
                         page_gbps=1024.0, prefetch_overlap=0.9)
    p99 = {}
    for lbl, f in (("no_paging", f_none), ("paged", f_page),
                   ("paged_prefetched", f_over)):
        p99[lbl] = simulate(trace, ct, f, seed=0,
                            record_events=False).summary()["p99_ms"]
    twin_ok = bool(p99["paged"] > p99["no_paging"]
                   and p99["paged_prefetched"] < p99["paged"])
    return {
        "rows": rows, "entry_words": e, "table_bytes": table_bytes,
        "plan": plan,
        "hosts_at_2x_table_bytes": plan2["hosts"],
        "hosts_memory_floor": floor,
        "memory_floor_binds": memory_bound,
        "jointly_monotone": jointly_monotone,
        "twin_fidelity": {
            "paging_stall_s_per_dispatch": round(
                f_page.paging_stall_s(), 6),
            "p99_ms": p99,
        },
        "checked": bool(memory_bound and jointly_monotone and twin_ok),
    }


# ------------------------------------------------------------ record


def bigtable_bench(n=8192, entry_size=8, cap=64, prf=0, *, hosts=2,
                   granules_per_host=4, budget_granules=2, seed=19,
                   duration_s=3.0, rate=24.0, slo_ms=2000.0, window=4,
                   distinct=16, native=False, quiet=False) -> dict:
    """All four legs over one seeded trace; returns the ``--bigtable``
    record (``BIGTABLE_r19.json``)."""
    if not native:
        from ..utils.hermetic import force_cpu_mesh
        force_cpu_mesh(8)
    from ..api import DPF
    from .buckets import Buckets

    FLIGHT.clear()      # scope the embedded flight events to this bench
    rng = np.random.default_rng(seed)
    table = rng.integers(-2 ** 31, 2 ** 31, size=(n, entry_size),
                         dtype=np.int64).astype(np.int32)
    oracle = DPF(prf=prf)
    oracle.eval_init(table)
    trace = loadgen.poisson_trace(rate=rate, duration_s=duration_s,
                                  cap=cap, seed=seed, n=n)
    buckets = Buckets.default_sizes(cap)
    pool = _key_pool(oracle, n, distinct, b"bigtable")
    slo_s = slo_ms / 1e3

    paged = _paged_cluster_leg(
        table, hosts=hosts, granules_per_host=granules_per_host,
        budget_granules=budget_granules, oracle=oracle, buckets=buckets,
        trace=trace, pool=pool, slo_s=slo_s, window=window)
    race = _prefetch_race_leg(
        table, oracle=oracle, pool=pool, trace=trace,
        ladder=buckets, granules=hosts * granules_per_host)
    mesh2d = _mesh2d_leg(prf=prf, seed=seed, dryrun=n <= 1024)
    plan = _plan_leg()

    total_escapes = (paged["gate_escapes"]
                     + race["prefetch_on"]["gate_rejections"]
                     + race["prefetch_off"]["gate_rejections"])
    record = {
        "metric": "billion-row table tier — paged granule residency "
                  "(device budget %d/%d granules per host, every "
                  "answer bit-gated vs the scalar oracle), prefetch-on "
                  "vs prefetch-off p99 under periodic residency "
                  "pressure, 2D row x entry-byte mesh parity, and "
                  "memory-aware fleet planning at 10^9 rows"
                  % (budget_granules, granules_per_host),
        "value": race["p99_speedup"],
        "unit": "x p99 (prefetch off / on)",
        "baseline": "the identical paged host replaying the identical "
                    "seeded trace under identical pressure with the "
                    "prefetcher disabled",
        "table": {"n": n, "entry_size": entry_size,
                  "bytes": n * entry_size * 4, "prf": prf},
        "trace": {"kind": "poisson", "seed": seed, "rate": rate,
                  "duration_s": duration_s, "cap": cap,
                  "arrivals": len(trace),
                  "queries": loadgen.total_queries(trace),
                  "window": window},
        "slo_ms": slo_ms,
        "paged_cluster": paged,
        "prefetch_race": race,
        "mesh_2d": mesh2d,
        "plan": plan,
        "swallowed_errors": swallowed_snapshot(),
        "gate_escapes": total_escapes,
        "checked": bool(total_escapes == 0 and paged["checked"]
                        and race["checked"] and mesh2d["checked"]
                        and plan["checked"]),
    }
    record["obs"] = record_sections()
    if not record["checked"]:
        # a failed gate is what the flight recorder exists to diagnose:
        # embed the FULL ring (every granule promote/evict/overcommit
        # with its store and row0, the scatter plans, the gate events)
        record["obs"]["flight_on_gate_failure"] = flight_dump()
        print("bigtable gate FAILED — full flight dump embedded in "
              "record (obs.flight_on_gate_failure, %d events)"
              % len(record["obs"]["flight_on_gate_failure"]),
              file=sys.stderr, flush=True)
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--entry-size", type=int, default=8)
    ap.add_argument("--cap", type=int, default=64)
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--seed", type=int, default=19)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--rate", type=float, default=24.0,
                    help="poisson arrival rate (arrivals/sec)")
    ap.add_argument("--slo-ms", type=float, default=2000.0)
    ap.add_argument("--native", action="store_true",
                    help="use the real device mesh instead of forcing "
                         "the 8-device CPU mesh (the relay TPU record)")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny trace/table smoke (CI): exercises every "
                         "leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    if args.dryrun:
        record = bigtable_bench(n=1024, entry_size=8, cap=16,
                                prf=args.prf, hosts=min(args.hosts, 2),
                                seed=args.seed, duration_s=1.0,
                                rate=16.0, slo_ms=args.slo_ms,
                                distinct=8, native=args.native)
    else:
        record = bigtable_bench(n=args.n, entry_size=args.entry_size,
                                cap=args.cap, prf=args.prf,
                                hosts=args.hosts, seed=args.seed,
                                duration_s=args.duration,
                                rate=args.rate, slo_ms=args.slo_ms,
                                native=args.native)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
