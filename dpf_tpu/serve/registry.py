"""Table registry: named, versioned tables with LRU device residency.

Serving millions of users means many tables under one process — more
table bytes than the accelerator holds.  The registry is the residency
arbiter the multi-tenant tier (``serve/tenant.py``) builds on:

* **Named + versioned** — ``register(name, table)`` uploads a new
  version (monotonic per name); ``acquire(name)`` answers with the
  latest (or a pinned explicit version), so a tenant can roll a table
  forward while in-flight queries finish against the version they
  started on.
* **Byte-budgeted LRU residency** — each version's prepared servers
  (one ``api.DPF`` per construction, ``build_servers``) keep the table
  device-resident while hot.  A configurable ``budget_bytes`` bounds
  total resident bytes: registering or re-promoting past the budget
  demotes the least-recently-used unpinned version to host RAM
  (``DPF.eval_free`` — the padded host table survives on the server),
  and a later ``acquire`` re-promotes it with a bit-identical
  ``eval_init`` re-upload.
* **Pinned versions** — ``acquire`` returns a ``TableLease`` (context
  manager) that PINS the version: a pinned version is never demoted out
  from under in-flight queries — eviction pressure marks it
  ``demote_pending`` and the demotion runs when the last lease
  releases.
* **Observable** — every promotion/demotion/eviction/overcommit is a
  ``FLIGHT.record("registry", ...)`` event and a counter
  (``note_swallowed``-style: counting never raises into the serving
  path), exported as ``dpf_registry_*`` metrics
  (``obs.metrics.register_table_registry``).

Budget accounting counts the POST-PADDING device bytes of every
construction layout (each construction uploads its own permutation of
the same table), so the resident-bytes gauge is what the device
actually holds, not what the caller passed in.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.flight import FLIGHT
from ..utils.profiling import note_swallowed
from .router import LABELS, build_servers

#: registry counter names (all monotonic)
COUNTER_NAMES = ("registrations", "promotions", "demotions", "evictions",
                 "deferred_demotions", "hits", "misses", "overcommits")


class TableVersion:
    """One registered (name, version): the host table, its prepared
    per-construction servers, and its residency state."""

    __slots__ = ("name", "version", "table", "servers", "nbytes",
                 "resident", "pins", "demote_pending", "last_used")

    def __init__(self, name, version, table, servers):
        self.name = name
        self.version = int(version)
        self.table = table            # caller's [N, E] host table
        self.servers = servers        # label -> prepared api.DPF
        # post-padding device bytes across every construction layout
        self.nbytes = sum(int(s.table.nbytes) for s in servers.values())
        self.resident = True
        self.pins = 0
        self.demote_pending = False
        self.last_used = 0            # registry LRU sequence

    @property
    def key(self) -> tuple:
        return (self.name, self.version)

    def __repr__(self):
        return ("TableVersion(%s@v%d, %.1f MiB, %s%s, pins=%d)"
                % (self.name, self.version, self.nbytes / 2 ** 20,
                   "resident" if self.resident else "host-ram",
                   ", demote_pending" if self.demote_pending else "",
                   self.pins))


class TableLease:
    """A pinned acquisition of one table version (context manager).

    While held, the version's device residency is guaranteed: queries
    dispatched through ``servers`` complete against the pinned upload
    even if eviction pressure arrives mid-flight (the demotion defers
    to the last release).  Idempotent ``release``.
    """

    __slots__ = ("_registry", "_tv", "_released")

    def __init__(self, registry, tv):
        self._registry = registry
        self._tv = tv
        self._released = False

    @property
    def name(self) -> str:
        return self._tv.name

    @property
    def version(self) -> int:
        return self._tv.version

    @property
    def servers(self) -> dict:
        return self._tv.servers

    def server(self, label: str):
        return self._tv.servers[label]

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._registry._release(self._tv)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TableRegistry:
    """Thread-safe named/versioned table store with LRU residency.

    Args:
      budget_bytes: total device bytes the registry may keep resident
        (None = unbounded).  Registering or promoting past the budget
        demotes LRU unpinned versions first; when everything else is
        pinned the registry OVERCOMMITS (serving in-flight traffic
        beats enforcing the budget) and counts it.
      labels: construction labels each version prepares
        (``router.LABELS`` by default — the full router race).
      prf_method: PRF id shared by every prepared server.
    """

    def __init__(self, budget_bytes: int | None = None, *,
                 labels=LABELS, prf_method: int = 0):
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self.labels = tuple(labels)
        self.prf_method = int(prf_method)
        self._tables = {}         # name -> {version -> TableVersion}
        self._lock = threading.RLock()
        self._seq = 0             # LRU clock
        self.counters = {k: 0 for k in COUNTER_NAMES}
        try:
            from ..obs.metrics import register_table_registry
            register_table_registry(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.registry.register_metrics", e)

    # ----------------------------------------------------- registration

    def register(self, name: str, table, version: int | None = None
                 ) -> TableVersion:
        """Upload ``table`` as a new version of ``name`` (monotonic
        version number when None).  Makes budget room FIRST (the new
        upload is the hottest thing in the process), then builds one
        prepared server per construction."""
        table = np.asarray(table)
        with self._lock:
            versions = self._tables.setdefault(name, {})
            if version is None:
                version = max(versions, default=0) + 1
            version = int(version)
            if version in versions:
                raise ValueError("table %r version %d already registered"
                                 % (name, version))
            self._ensure_budget(self._estimate_bytes(table))
            servers = build_servers(table, self.labels,
                                    prf_method=self.prf_method)
            tv = TableVersion(name, version, table, servers)
            self._touch(tv)
            versions[version] = tv
            self.counters["registrations"] += 1
            self._event("register", tv)
            return tv

    def _estimate_bytes(self, table) -> int:
        """Device bytes ``register`` will occupy: per-construction
        padded int32 layout (the pow-of-two pad rule of
        ``DPF.eval_init``)."""
        n, e = table.shape
        if n & (n - 1) != 0:
            n = 1 << n.bit_length()
        return n * e * 4 * len(self.labels)

    # ------------------------------------------------------- residency

    def acquire(self, name: str, version: int | None = None
                ) -> TableLease:
        """Pin (and, when cold, re-promote) a version; latest when
        ``version`` is None.  Returns a ``TableLease``."""
        with self._lock:
            tv = self._get(name, version)
            if tv.resident:
                self.counters["hits"] += 1
            else:
                self.counters["misses"] += 1
                self._promote(tv)
            tv.pins += 1
            self._touch(tv)
            return TableLease(self, tv)

    def demote(self, name: str, version: int | None = None) -> bool:
        """Demote a version's device residency to host RAM.  A pinned
        version only gets ``demote_pending`` (in-flight queries finish
        against the pinned upload; the demotion runs at last release).
        Returns True when the demotion happened now."""
        with self._lock:
            tv = self._get(name, version)
            return self._demote(tv, action="demote")

    def _get(self, name, version) -> TableVersion:
        versions = self._tables.get(name)
        if not versions:
            raise KeyError("no table registered as %r" % (name,))
        if version is None:
            version = max(versions)
        if version not in versions:
            raise KeyError("table %r has no version %s (have %s)"
                           % (name, version, sorted(versions)))
        return versions[version]

    def _touch(self, tv) -> None:
        self._seq += 1
        tv.last_used = self._seq

    def _promote(self, tv) -> None:
        """Re-upload a demoted version (bit-identical: ``eval_init``
        over the SAME padded host table each server kept)."""
        self._ensure_budget(tv.nbytes, keep=tv)
        for srv in tv.servers.values():
            srv.eval_init(srv.table)
        tv.resident = True
        tv.demote_pending = False
        self.counters["promotions"] += 1
        self._event("promote", tv)

    def _demote(self, tv, action: str) -> bool:
        if not tv.resident:
            return False
        if tv.pins > 0:
            if not tv.demote_pending:
                tv.demote_pending = True
                self.counters["deferred_demotions"] += 1
                self._event("demote_deferred", tv)
            return False
        for srv in tv.servers.values():
            srv.eval_free()
        tv.resident = False
        tv.demote_pending = False
        self.counters["demotions"] += 1
        if action == "evict":
            self.counters["evictions"] += 1
        self._event(action, tv)
        return True

    def _ensure_budget(self, need: int, keep=None) -> None:
        """Demote LRU resident unpinned versions until ``need`` more
        bytes fit; overcommit (counted) when everything left is
        pinned."""
        if self.budget_bytes is None:
            return
        while self.resident_bytes + need > self.budget_bytes:
            victims = [tv for tv in self._versions()
                       if tv.resident and tv.pins == 0
                       and tv is not keep]
            if not victims:
                self.counters["overcommits"] += 1
                FLIGHT.record("registry", action="overcommit",
                              need_bytes=int(need),
                              resident_bytes=self.resident_bytes,
                              budget_bytes=self.budget_bytes)
                return
            self._demote(min(victims, key=lambda tv: tv.last_used),
                         action="evict")

    def _release(self, tv) -> None:
        with self._lock:
            tv.pins = max(0, tv.pins - 1)
            if tv.pins == 0 and tv.demote_pending:
                self._demote(tv, action="demote")

    # -------------------------------------------------------- plumbing

    def _versions(self):
        for versions in self._tables.values():
            yield from versions.values()

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(tv.nbytes for tv in self._versions()
                       if tv.resident)

    def _event(self, action: str, tv) -> None:
        FLIGHT.record("registry", action=action, table=tv.name,
                      version=tv.version, bytes=tv.nbytes,
                      pins=tv.pins, resident_bytes=self.resident_bytes)

    def stats(self) -> dict:
        """JSON-ready registry snapshot (benchmark records embed it)."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes,
                "counters": dict(self.counters),
                "tables": [{"name": tv.name, "version": tv.version,
                            "bytes": tv.nbytes,
                            "resident": tv.resident, "pins": tv.pins,
                            "demote_pending": tv.demote_pending}
                           for tv in sorted(self._versions(),
                                            key=lambda t: t.key)],
            }

    def __repr__(self):
        st = self.stats()
        return ("TableRegistry(%d tables, %.1f/%s MiB resident)"
                % (len(st["tables"]), st["resident_bytes"] / 2 ** 20,
                   "inf" if self.budget_bytes is None
                   else "%.1f" % (self.budget_bytes / 2 ** 20)))
