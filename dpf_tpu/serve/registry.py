"""Table registry: named, versioned tables with LRU device residency.

Serving millions of users means many tables under one process — more
table bytes than the accelerator holds.  The registry is the residency
arbiter the multi-tenant tier (``serve/tenant.py``) builds on:

* **Named + versioned** — ``register(name, table)`` uploads a new
  version (monotonic per name); ``acquire(name)`` answers with the
  latest (or a pinned explicit version), so a tenant can roll a table
  forward while in-flight queries finish against the version they
  started on.
* **Byte-budgeted LRU residency** — each version's prepared servers
  (one ``api.DPF`` per construction, ``build_servers``) keep the table
  device-resident while hot.  A configurable ``budget_bytes`` bounds
  total resident bytes: registering or re-promoting past the budget
  demotes the least-recently-used unpinned version to host RAM
  (``DPF.eval_free`` — the padded host table survives on the server),
  and a later ``acquire`` re-promotes it with a bit-identical
  ``eval_init`` re-upload.
* **Pinned versions** — ``acquire`` returns a ``TableLease`` (context
  manager) that PINS the version: a pinned version is never demoted out
  from under in-flight queries — eviction pressure marks it
  ``demote_pending`` and the demotion runs when the last lease
  releases.
* **Observable** — every promotion/demotion/eviction/overcommit is a
  ``FLIGHT.record("registry", ...)`` event and a counter
  (``note_swallowed``-style: counting never raises into the serving
  path), exported as ``dpf_registry_*`` metrics
  (``obs.metrics.register_table_registry``).

Budget accounting counts the POST-PADDING device bytes of every
construction layout (each construction uploads its own permutation of
the same table), so the resident-bytes gauge is what the device
actually holds, not what the caller passed in.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.flight import FLIGHT
from ..utils.profiling import note_swallowed
from .router import LABELS, build_servers

#: registry counter names (all monotonic)
COUNTER_NAMES = ("registrations", "promotions", "demotions", "evictions",
                 "deferred_demotions", "hits", "misses", "overcommits")


class TableVersion:
    """One registered (name, version): the host table, its prepared
    per-construction servers, and its residency state."""

    __slots__ = ("name", "version", "table", "servers", "nbytes",
                 "resident", "pins", "demote_pending", "last_used")

    def __init__(self, name, version, table, servers):
        self.name = name
        self.version = int(version)
        self.table = table            # caller's [N, E] host table
        self.servers = servers        # label -> prepared api.DPF
        # post-padding device bytes across every construction layout
        self.nbytes = sum(int(s.table.nbytes) for s in servers.values())
        self.resident = True
        self.pins = 0
        self.demote_pending = False
        self.last_used = 0            # registry LRU sequence

    @property
    def key(self) -> tuple:
        return (self.name, self.version)

    def __repr__(self):
        return ("TableVersion(%s@v%d, %.1f MiB, %s%s, pins=%d)"
                % (self.name, self.version, self.nbytes / 2 ** 20,
                   "resident" if self.resident else "host-ram",
                   ", demote_pending" if self.demote_pending else "",
                   self.pins))


class TableLease:
    """A pinned acquisition of one table version (context manager).

    While held, the version's device residency is guaranteed: queries
    dispatched through ``servers`` complete against the pinned upload
    even if eviction pressure arrives mid-flight (the demotion defers
    to the last release).  Idempotent ``release``.
    """

    __slots__ = ("_registry", "_tv", "_released")

    def __init__(self, registry, tv):
        self._registry = registry
        self._tv = tv
        self._released = False

    @property
    def name(self) -> str:
        return self._tv.name

    @property
    def version(self) -> int:
        return self._tv.version

    @property
    def servers(self) -> dict:
        return self._tv.servers

    def server(self, label: str):
        return self._tv.servers[label]

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._registry._release(self._tv)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TableRegistry:
    """Thread-safe named/versioned table store with LRU residency.

    Args:
      budget_bytes: total device bytes the registry may keep resident
        (None = unbounded).  Registering or promoting past the budget
        demotes LRU unpinned versions first; when everything else is
        pinned the registry OVERCOMMITS (serving in-flight traffic
        beats enforcing the budget) and counts it.
      labels: construction labels each version prepares
        (``router.LABELS`` by default — the full router race).
      prf_method: PRF id shared by every prepared server.
    """

    def __init__(self, budget_bytes: int | None = None, *,
                 labels=LABELS, prf_method: int = 0):
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self.labels = tuple(labels)
        self.prf_method = int(prf_method)
        self._tables = {}         # name -> {version -> TableVersion}
        self._lock = threading.RLock()
        self._seq = 0             # LRU clock
        self.counters = {k: 0 for k in COUNTER_NAMES}
        try:
            from ..obs.metrics import register_table_registry
            register_table_registry(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.registry.register_metrics", e)

    # ----------------------------------------------------- registration

    def register(self, name: str, table, version: int | None = None
                 ) -> TableVersion:
        """Upload ``table`` as a new version of ``name`` (monotonic
        version number when None).  Makes budget room FIRST (the new
        upload is the hottest thing in the process), then builds one
        prepared server per construction."""
        table = np.asarray(table)
        with self._lock:
            versions = self._tables.setdefault(name, {})
            if version is None:
                version = max(versions, default=0) + 1
            version = int(version)
            if version in versions:
                raise ValueError("table %r version %d already registered"
                                 % (name, version))
            self._ensure_budget(self._estimate_bytes(table))
            servers = build_servers(table, self.labels,
                                    prf_method=self.prf_method)
            tv = TableVersion(name, version, table, servers)
            self._touch(tv)
            versions[version] = tv
            self.counters["registrations"] += 1
            self._event("register", tv)
            return tv

    def _estimate_bytes(self, table) -> int:
        """Device bytes ``register`` will occupy: per-construction
        padded int32 layout (the pow-of-two pad rule of
        ``DPF.eval_init``)."""
        n, e = table.shape
        if n & (n - 1) != 0:
            n = 1 << n.bit_length()
        return n * e * 4 * len(self.labels)

    # ------------------------------------------------------- residency

    def acquire(self, name: str, version: int | None = None
                ) -> TableLease:
        """Pin (and, when cold, re-promote) a version; latest when
        ``version`` is None.  Returns a ``TableLease``."""
        with self._lock:
            tv = self._get(name, version)
            if tv.resident:
                self.counters["hits"] += 1
            else:
                self.counters["misses"] += 1
                self._promote(tv)
            tv.pins += 1
            self._touch(tv)
            return TableLease(self, tv)

    def demote(self, name: str, version: int | None = None) -> bool:
        """Demote a version's device residency to host RAM.  A pinned
        version only gets ``demote_pending`` (in-flight queries finish
        against the pinned upload; the demotion runs at last release).
        Returns True when the demotion happened now."""
        with self._lock:
            tv = self._get(name, version)
            return self._demote(tv, action="demote")

    def _get(self, name, version) -> TableVersion:
        versions = self._tables.get(name)
        if not versions:
            raise KeyError("no table registered as %r" % (name,))
        if version is None:
            version = max(versions)
        if version not in versions:
            raise KeyError("table %r has no version %s (have %s)"
                           % (name, version, sorted(versions)))
        return versions[version]

    def _touch(self, tv) -> None:
        self._seq += 1
        tv.last_used = self._seq

    def _promote(self, tv) -> None:
        """Re-upload a demoted version (bit-identical: ``eval_init``
        over the SAME padded host table each server kept)."""
        self._ensure_budget(tv.nbytes, keep=tv)
        for srv in tv.servers.values():
            srv.eval_init(srv.table)
        tv.resident = True
        tv.demote_pending = False
        self.counters["promotions"] += 1
        self._event("promote", tv)

    def _demote(self, tv, action: str) -> bool:
        if not tv.resident:
            return False
        if tv.pins > 0:
            if not tv.demote_pending:
                tv.demote_pending = True
                self.counters["deferred_demotions"] += 1
                self._event("demote_deferred", tv)
            return False
        for srv in tv.servers.values():
            srv.eval_free()
        tv.resident = False
        tv.demote_pending = False
        self.counters["demotions"] += 1
        if action == "evict":
            self.counters["evictions"] += 1
        self._event(action, tv)
        return True

    def _ensure_budget(self, need: int, keep=None) -> None:
        """Demote LRU resident unpinned versions until ``need`` more
        bytes fit; overcommit (counted) when everything left is
        pinned."""
        if self.budget_bytes is None:
            return
        while self.resident_bytes + need > self.budget_bytes:
            victims = [tv for tv in self._versions()
                       if tv.resident and tv.pins == 0
                       and tv is not keep]
            if not victims:
                self.counters["overcommits"] += 1
                FLIGHT.record("registry", action="overcommit",
                              need_bytes=int(need),
                              resident_bytes=self.resident_bytes,
                              budget_bytes=self.budget_bytes)
                return
            self._demote(min(victims, key=lambda tv: tv.last_used),
                         action="evict")

    def _release(self, tv) -> None:
        with self._lock:
            tv.pins = max(0, tv.pins - 1)
            if tv.pins == 0 and tv.demote_pending:
                self._demote(tv, action="demote")

    # -------------------------------------------------------- plumbing

    def _versions(self):
        for versions in self._tables.values():
            yield from versions.values()

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(tv.nbytes for tv in self._versions()
                       if tv.resident)

    def _event(self, action: str, tv) -> None:
        FLIGHT.record("registry", action=action, table=tv.name,
                      version=tv.version, bytes=tv.nbytes,
                      pins=tv.pins, resident_bytes=self.resident_bytes)

    def stats(self) -> dict:
        """JSON-ready registry snapshot (benchmark records embed it)."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes,
                "counters": dict(self.counters),
                "tables": [{"name": tv.name, "version": tv.version,
                            "bytes": tv.nbytes,
                            "resident": tv.resident, "pins": tv.pins,
                            "demote_pending": tv.demote_pending}
                           for tv in sorted(self._versions(),
                                            key=lambda t: t.key)],
            }

    def __repr__(self):
        st = self.stats()
        return ("TableRegistry(%d tables, %.1f/%s MiB resident)"
                % (len(st["tables"]), st["resident_bytes"] / 2 ** 20,
                   "inf" if self.budget_bytes is None
                   else "%.1f" % (self.budget_bytes / 2 ** 20)))

    def granule_store(self, name: str, version: int | None = None, *,
                      granule: int, budget_bytes: int | None = None
                      ) -> "GranuleStore":
        """Granule-level residency over one registered version's table
        (the big-table tier: residency finer than whole-table LRU).
        The store pages the binary-GGM PERMUTED layout — the same bytes
        a ``ClusterShardServer`` granule holds, the layout
        ``eval_leaf_range_local`` contracts — so a paged partial eval
        is bit-identical to the always-resident one."""
        from ..core import expand
        with self._lock:
            tv = self._get(name, version)
            srv = tv.servers["logn"]
            perm = expand.permute_table(
                np.asarray(srv.table, dtype=np.int32))
            return GranuleStore(perm, granule,
                                budget_bytes=budget_bytes,
                                name="%s@v%d" % (tv.name, tv.version))


# --------------------------------------------------- granule residency

#: granule-store counter names (all monotonic)
GRANULE_COUNTER_NAMES = ("promotions", "demotions", "evictions",
                         "deferred_demotions", "hits", "misses",
                         "prefetches", "prefetch_hits",
                         "prefetch_misses", "overcommits")


class GranuleLease:
    """A pinned acquisition of one device-resident granule (context
    manager) — the granule-level twin of ``TableLease``.  While held,
    the granule cannot be demoted out from under an in-flight partial
    eval: pressure marks it ``demote_pending`` and the demotion runs at
    the last release.  Idempotent ``release``."""

    __slots__ = ("_store", "row0", "table", "_released")

    def __init__(self, store, row0, table):
        self._store = store
        self.row0 = row0
        self.table = table            # the device-resident [granule, E]
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._store._release(self.row0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class GranuleStore:
    """Granule-level HBM residency for one permuted table.

    ``TableRegistry`` arbitrates residency between whole tables; this
    store arbitrates WITHIN one — the big-table tier where a single
    logical table exceeds the device budget.  The host master copy (the
    construction's permuted layout) lives in host RAM; granules —
    contiguous ``granule``-row slices, the same unit the multi-host
    cluster scatters — are promoted to the device on demand
    (``lease``), ahead of demand (``prefetch``, driven by
    ``GranulePrefetcher``), and demoted LRU-first when ``budget_bytes``
    pressure arrives.  Promotion is ``device_put`` of the SAME host
    bytes every time, so a granule that crosses an eviction boundary
    mid-stream comes back bit-identical and every paged partial eval
    matches the always-resident answer exactly.

    Pinning follows the registry's lease discipline: a leased granule
    is never demoted mid-flight (pressure defers to the last release,
    counted as ``deferred_demotions``); when every resident granule is
    pinned the store overcommits rather than stall serving (counted).
    Thread-safe; every transition is a ``FLIGHT.record("registry",
    granule=...)`` event and a counter, exported as
    ``dpf_registry_granule*`` metrics
    (``obs.metrics.register_granule_store``).
    """

    def __init__(self, table_perm, granule: int, *,
                 budget_bytes: int | None = None, name: str = "table"):
        tbl = np.asarray(table_perm, dtype=np.int32)
        n = tbl.shape[0]
        granule = int(granule)
        if granule < 1 or n % granule:
            raise ValueError("granule %d must divide %d rows"
                             % (granule, n))
        self.name = str(name)
        self.granule = granule
        self.n, self.entry_size = tbl.shape
        self._host = np.ascontiguousarray(tbl)   # host-RAM master copy
        self.row0s = tuple(range(0, n, granule))
        self.granule_bytes = granule * self.entry_size * 4
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self._resident = {}        # row0 -> device [granule, E]
        self._pins = {}            # row0 -> pin count
        self._demote_pending = set()
        self._prefetched = set()   # resident via prefetch, not yet hit
        self._last_used = {}       # row0 -> LRU sequence
        self._seq = 0
        self._page_s = None        # EWMA seconds per promotion
        self._lock = threading.RLock()
        self.counters = {k: 0 for k in GRANULE_COUNTER_NAMES}
        try:
            from ..obs.metrics import register_granule_store
            register_granule_store(self)
        except Exception as e:  # observability must never break serving
            note_swallowed("serve.registry.register_granule_metrics", e)

    # ------------------------------------------------------- residency

    def lease(self, row0: int) -> GranuleLease:
        """Pin granule ``row0`` device-resident (demand-promoting a
        cold one) and return its ``GranuleLease``.  A hit on a granule
        a prefetch brought in counts ``prefetch_hits``; a demand
        promotion counts ``prefetch_misses`` — the prefetcher's
        scoreboard."""
        with self._lock:
            if row0 in self._resident:
                self.counters["hits"] += 1
                if row0 in self._prefetched:
                    self._prefetched.discard(row0)
                    self.counters["prefetch_hits"] += 1
            else:
                self.counters["misses"] += 1
                self.counters["prefetch_misses"] += 1
                self._promote(row0, prefetch=False)
            self._pins[row0] = self._pins.get(row0, 0) + 1
            self._touch(row0)
            return GranuleLease(self, row0, self._resident[row0])

    def prefetch(self, row0: int | None = None) -> bool:
        """Promote one cold granule (``row0``, or the lowest cold one)
        into FREE budget — a prefetch never evicts: paging ahead of a
        guess must not displace granules demand is using.  Returns True
        when a promotion happened."""
        with self._lock:
            if row0 is None:
                cold = self.cold_row0s()
                if not cold:
                    return False
                row0 = cold[0]
            if row0 in self._resident:
                return False
            if (self.budget_bytes is not None
                    and self.resident_bytes + self.granule_bytes
                    > self.budget_bytes):
                return False
            self._promote(row0, prefetch=True)
            self._prefetched.add(row0)
            self.counters["prefetches"] += 1
            self._touch(row0)
            return True

    def demote(self, row0: int) -> bool:
        """Demote one granule to host-RAM-only residency (its bytes
        stay in the master copy — demotion just drops the device
        buffer).  Pinned granules defer to the last release.  Returns
        True when the demotion happened now."""
        with self._lock:
            return self._demote(row0, action="granule_demote")

    def demote_all(self) -> int:
        """Demote every unpinned resident granule (registry-level
        pressure: another table claimed the device).  Returns how many
        demoted now."""
        with self._lock:
            return sum(self._demote(r, action="granule_demote")
                       for r in list(self._resident))

    # ------------------------------------------------------- internals

    def _touch(self, row0) -> None:
        self._seq += 1
        self._last_used[row0] = self._seq

    def _promote(self, row0, prefetch: bool) -> None:
        if row0 % self.granule or not 0 <= row0 < self.n:
            raise KeyError("granule row0=%d not in store (granule=%d, "
                           "n=%d)" % (row0, self.granule, self.n))
        import time

        import jax
        self._ensure_budget(self.granule_bytes, keep=row0)
        t0 = time.perf_counter()
        arr = jax.device_put(self._host[row0:row0 + self.granule])
        arr.block_until_ready()
        dt = time.perf_counter() - t0
        self._page_s = (dt if self._page_s is None
                        else 0.25 * dt + 0.75 * self._page_s)
        self._resident[row0] = arr
        self._demote_pending.discard(row0)
        self.counters["promotions"] += 1
        self._event("granule_promote", row0, prefetch=prefetch)

    def _demote(self, row0, action: str) -> bool:
        if row0 not in self._resident:
            return False
        if self._pins.get(row0, 0) > 0:
            if row0 not in self._demote_pending:
                self._demote_pending.add(row0)
                self.counters["deferred_demotions"] += 1
                self._event("granule_demote_deferred", row0)
            return False
        del self._resident[row0]      # device buffer freed with the ref
        self._demote_pending.discard(row0)
        self._prefetched.discard(row0)
        self.counters["demotions"] += 1
        if action == "granule_evict":
            self.counters["evictions"] += 1
        self._event(action, row0)
        return True

    def _ensure_budget(self, need: int, keep=None) -> None:
        if self.budget_bytes is None:
            return
        while self.resident_bytes + need > self.budget_bytes:
            victims = [r for r in self._resident
                       if self._pins.get(r, 0) == 0 and r != keep]
            if not victims:
                self.counters["overcommits"] += 1
                FLIGHT.record("registry", action="granule_overcommit",
                              store=self.name, need_bytes=int(need),
                              resident_bytes=self.resident_bytes,
                              budget_bytes=self.budget_bytes)
                return
            self._demote(min(victims,
                             key=lambda r: self._last_used.get(r, 0)),
                         action="granule_evict")

    def _release(self, row0) -> None:
        with self._lock:
            self._pins[row0] = max(0, self._pins.get(row0, 0) - 1)
            if (self._pins[row0] == 0
                    and row0 in self._demote_pending):
                self._demote(row0, action="granule_demote")

    def _event(self, action: str, row0, **extra) -> None:
        FLIGHT.record("registry", action=action, store=self.name,
                      granule=int(row0),
                      pins=self._pins.get(row0, 0),
                      resident=len(self._resident), **extra)

    # -------------------------------------------------------- plumbing

    @property
    def resident_bytes(self) -> int:
        return len(self._resident) * self.granule_bytes

    def resident_row0s(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._resident))

    def cold_row0s(self) -> tuple:
        with self._lock:
            return tuple(r for r in self.row0s
                         if r not in self._resident)

    @property
    def page_s(self) -> float | None:
        """EWMA seconds per granule promotion (None until measured) —
        how the prefetcher sizes its between-arrivals window."""
        return self._page_s

    def stats(self) -> dict:
        """JSON-ready store snapshot (benchmark records embed it)."""
        with self._lock:
            return {
                "name": self.name,
                "granule": self.granule,
                "granules": len(self.row0s),
                "granules_resident": len(self._resident),
                "granule_bytes": self.granule_bytes,
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes,
                "page_s_ewma": self._page_s,
                "counters": dict(self.counters),
            }

    def __repr__(self):
        return ("GranuleStore(%s, %d/%d granules resident, %.1f/%s MiB)"
                % (self.name, len(self._resident), len(self.row0s),
                   self.resident_bytes / 2 ** 20,
                   "inf" if self.budget_bytes is None
                   else "%.1f" % (self.budget_bytes / 2 ** 20)))


class GranulePrefetcher:
    """Pages cold granules in BETWEEN arrivals so the device_put cost
    overlaps serving instead of landing on a query's critical path.

    ``tick()`` runs in idle gaps (the serving loop calls it after each
    batch resolves, or a maintenance thread calls it on a timer) and
    promotes up to ``max_per_tick`` cold granules into free budget.
    With ``rates_fn`` — the router's live per-bucket arrival-rate
    estimate (``SchemeRouter.arrival_rates``, or the offline
    ``loadgen.bucket_rates``) — the tick sizes itself to the expected
    idle window: at total arrival rate R the next batch lands in ~1/R
    seconds, so it schedules at most ``slack/R / page_s`` promotions
    (measured EWMA ``GranuleStore.page_s``), never a page-in it expects
    to collide with the next arrival.  Prefetch never evicts
    (``GranuleStore.prefetch``), so a mis-estimated rate costs only
    staler cold granules, never thrash."""

    def __init__(self, store: GranuleStore, *, rates_fn=None,
                 max_per_tick: int = 4, slack: float = 0.5):
        if max_per_tick < 1:
            raise ValueError("max_per_tick must be >= 1")
        if not 0 < slack <= 1:
            raise ValueError("slack must be in (0, 1] (got %r)"
                             % (slack,))
        self.store = store
        self.rates_fn = rates_fn
        self.max_per_tick = int(max_per_tick)
        self.slack = float(slack)
        self.ticks = 0
        self.promoted = 0

    def budget_this_tick(self) -> int:
        """How many promotions this tick may issue: ``max_per_tick``
        capped to what fits the expected idle window."""
        allowed = self.max_per_tick
        page_s = self.store.page_s
        if self.rates_fn is not None and page_s:
            try:
                total_hz = sum(self.rates_fn().values())
            except Exception as e:  # estimator must never break paging
                note_swallowed("serve.registry.prefetch_rates", e)
                total_hz = 0.0
            if total_hz > 0:
                window = self.slack / total_hz
                allowed = min(allowed, max(1, int(window / page_s)))
        return allowed

    def tick(self) -> int:
        """Promote cold granules (lowest row0 first — dispatch order)
        into free budget; returns how many promotions happened."""
        self.ticks += 1
        done = 0
        for _ in range(self.budget_this_tick()):
            if not self.store.prefetch():
                break
            done += 1
        self.promoted += done
        return done

    def stats(self) -> dict:
        return {"ticks": self.ticks, "promoted": self.promoted,
                "max_per_tick": self.max_per_tick, "slack": self.slack}
