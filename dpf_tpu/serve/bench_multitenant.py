"""Multi-tenant noisy-neighbor chaos bench: isolation under one process.

``benchmark.py --multitenant``.  Serves N tenants — distinct (N, E)
tables plus one tenant SHARING another's table and bucket ladder —
through one ``TenantRouter`` (``serve/tenant.py``) over one
``TableRegistry``, and measures per-tenant SLO attainment across three
legs over the same seeded open-loop traces:

1. **solo** — each tenant's trace replayed alone (the baseline every
   isolation tolerance is measured against).
2. **combined** — every tenant's trace merged by timestamp and
   replayed concurrently under the deficit-round-robin scheduler.
3. **noisy-neighbor chaos** — the victim tenant's trace is squeezed 4x
   (burst) AND its router runs a seeded ``FaultPlan`` (dispatch errors
   + an engine death).  The victim degrades — counted sheds, absorbed
   faults — while every OTHER tenant must hold availability 1.0 and
   p99 within ``tolerance`` (1.5x) of its solo baseline.

Every served batch in every leg is bit-gated against the scalar oracle
(``DPF.eval_cpu`` reference shares); ``checked`` requires >= 3 distinct
(N, E) shapes, 0 gate escapes, full non-victim isolation in the chaos
leg, a degraded victim, and per-tenant series visible in the embedded
metrics/flight sections.  The committed record is
``MULTITENANT_r16.json``; the fault plan is serialized into the record
(``faults.plan``) so the sequence is exactly replayable.

  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benchmark.py --multitenant [--dryrun] [--out FILE]
"""

from __future__ import annotations

import json
import queue
import threading
import time

import numpy as np

from ..obs import FLIGHT, record_sections
from . import loadgen
from .bench_load import _batch_for, _key_pool, _slo_stats
from .engine import LoadShed
from .faults import FaultPlan, FaultSpec, RetryPolicy
from .registry import TableRegistry
from .tenant import TenantRouter, TenantSpec

#: non-victim p99 tolerance vs solo baseline in the chaos leg
TOLERANCE = 1.5
#: additive floor for the p99 ratio gate.  The solo baseline has zero
#: cross-tenant overlap by construction, but on the 1-core CPU
#: rehearsal two coincident cap-sized batches serialize at the XLA
#: level (~4 ms each; no scheduler can preempt a dispatched program),
#: so any concurrent leg's p99 — the top sample of ~100 — sits one
#: overlap quantum (up to ~3 stacked batches) above solo even with no
#: victim at all.  The ratio therefore only binds once the absolute
#: delta exceeds this quantum; on a real TPU the device pipeline
#: shrinks it (relay item in ROADMAP.md).
SLACK_MS = 12.0


def _mk_trace(cfg: dict, seed: int, duration_s: float) -> list:
    return loadgen.bursty_trace(
        on_rate=cfg["on_rate"], off_rate=cfg["on_rate"] / 8.0,
        on_s=0.6, off_s=0.6, duration_s=duration_s, cap=cfg["cap"],
        seed=seed, n=cfg["n"])


def _merge(traces: dict) -> list:
    """Merge per-tenant traces into one (tenant, arrival,
    tenant-local j) stream ordered by scheduled time."""
    tagged = []
    for name, trace in traces.items():
        tagged.extend((a.t, name, a, j) for j, a in enumerate(trace))
    tagged.sort(key=lambda r: r[0])
    return [(name, a, j) for _, name, a, j in tagged]


def _replay_mt(tr: TenantRouter, tagged, pools, *,
               inject: bool = False):
    """Open-loop replay of a merged multi-tenant stream.

    Submission is strictly on the trace schedule (open loop, one
    thread; latency = completion − scheduled arrival).  Each tenant
    gets its OWN resolver thread: one tenant's slow batches must never
    delay the point where another tenant's completions are *measured*,
    or the victim's chaos leg would inflate every bystander's p99
    purely through the measurement loop.  A tenant's shed (at submit
    OR at dispatch) and fault-exhausted errors are THAT tenant's
    unavailability, never an exception out of the loop.  Arrival
    indices only reach the fault injector when ``inject`` is True (the
    chaos leg) — the solo/combined legs stay fault-free.  Returns
    ``(lats, done, fails, sheds, makespan_s)`` — ``lats`` per tenant
    for ok batches, ``done`` the gate's (tenant, arrival, j, future)
    list, ``fails``/``sheds`` per-tenant counts.
    """
    names = {name for name, _, _ in tagged}
    lats = {n: [] for n in names}
    fails = {n: 0 for n in names}
    sheds = {n: 0 for n in names}     # resolver threads only
    admit_sheds = {n: 0 for n in names}   # submit thread only
    done = {n: [] for n in names}
    queues = {n: queue.Queue() for n in names}
    t0 = time.perf_counter()

    def resolver(name):
        while True:
            item = queues[name].get()
            if item is None:
                return
            a, j, fut = item
            try:
                fut.result()
            except LoadShed:
                sheds[name] += 1
                continue
            except Exception:
                fails[name] += 1
                continue
            lats[name].append((time.perf_counter() - t0) - a.t)
            done[name].append((name, a, j, fut))

    threads = [threading.Thread(target=resolver, args=(n,), daemon=True)
               for n in names]
    for th in threads:
        th.start()
    for name, a, j in tagged:
        while True:
            now = time.perf_counter() - t0
            if now >= a.t:
                break
            time.sleep(min(a.t - now, 0.005))

        def keys_for(lb, _name=name, _j=j, _b=a.batch):
            return _batch_for(pools[_name][lb], _j, _b)[0]
        try:
            fut = tr.submit(name, a.batch, keys_for,
                            arrival=j if inject else None)
        except LoadShed:
            admit_sheds[name] += 1
            continue
        queues[name].put((a, j, fut))
    for n in names:
        queues[n].put(None)
    for th in threads:
        th.join()
    for n in names:
        sheds[n] += admit_sheds[n]
    all_done = [x for n in sorted(names) for x in done[n]]
    return lats, all_done, fails, sheds, time.perf_counter() - t0


def _leg_stats(traces, lats, fails, sheds, escapes_by, slo_s) -> dict:
    out = {}
    for name, trace in traces.items():
        arrivals = len(trace)
        esc = escapes_by.get(name, 0)
        ok = len(lats[name]) - esc
        out[name] = {
            "arrivals": arrivals,
            "ok_batches": ok,
            "shed_batches": sheds[name],
            "failed_batches": fails[name],
            "gate_escapes": esc,
            "availability": (round(ok / arrivals, 4) if arrivals
                             else None),
            **_slo_stats(lats[name], slo_s),
        }
    return out


def _escapes_by_tenant(done, pools) -> dict:
    by = {}
    for name, a, j, fut in done:
        label = fut.decision.construction
        _, refs = pools[name][label]
        _, idxs = _batch_for(pools[name][label], j, a.batch)
        if not np.array_equal(fut.result(), refs[idxs]):
            by[name] = by.get(name, 0) + 1
    return by


def multitenant_bench(*, seed: int = 16, duration_s: float = 5.0,
                      slo_ms: float = 400.0,
                      burst: float = 4.0, prf: int = 0,
                      distinct: int = 8, dryrun: bool = False,
                      quiet: bool = False) -> dict:
    """Serve >= 3 distinct-(N, E) tenants (plus one table-sharing
    tenant) under one process and gate the noisy-neighbor isolation
    claim; returns the ``--multitenant`` record."""
    FLIGHT.clear()      # scope the embedded flight tail to this bench
    if dryrun:
        cfgs = {
            "alpha": dict(n=512, e=8, cap=16, on_rate=16.0, weight=1.0),
            "bravo": dict(n=256, e=4, cap=16, on_rate=16.0, weight=1.0),
            "victim": dict(n=128, e=4, cap=8, on_rate=24.0, weight=1.0),
            "delta": dict(n=512, e=8, cap=16, on_rate=12.0, weight=1.0,
                          table_name="alpha"),
        }
    else:
        cfgs = {
            "alpha": dict(n=4096, e=16, cap=64, on_rate=24.0,
                          weight=1.0),
            "bravo": dict(n=2048, e=8, cap=64, on_rate=24.0,
                          weight=1.0),
            "victim": dict(n=1024, e=4, cap=32, on_rate=40.0,
                           weight=1.0),
            "delta": dict(n=4096, e=16, cap=64, on_rate=16.0,
                          weight=1.0, table_name="alpha"),
        }
    victim = "victim"
    slo_s = slo_ms / 1e3

    # ---- the victim's seeded fault plan (chaos leg only: specs match
    # arrival indices, and arrivals are only threaded in that leg).
    # The dispatch-error window is p=1.0 across ALL constructions so
    # retry + failover cannot absorb it — the victim MUST degrade. ----
    plan = FaultPlan([
        FaultSpec("dispatch_error", p=1.0, start=2, stop=6),
        FaultSpec("engine_death", construction="logn", start=6),
    ], seed=seed)

    # ---- one registry + tenant router over all tables ----------------
    rng = np.random.default_rng(seed ^ 0x7e4a47)
    registry = TableRegistry(prf_method=prf)
    tr = TenantRouter(registry)
    tables = {}
    for name, cfg in cfgs.items():
        shared = cfg.get("table_name")
        if shared is None:
            tables[name] = rng.integers(0, 2 ** 31, (cfg["n"], cfg["e"]),
                                        dtype=np.int32, endpoint=False)
        spec = TenantSpec(
            name,
            table=None if shared else tables[name],
            table_name=shared,
            weight=cfg["weight"], cap=cfg["cap"], slo_s=slo_s,
            max_in_flight=2 if name == victim else 4,
            max_queue_depth=4 if name == victim else None,
            shed=(name == victim),
            plan=plan if name == victim else None,
            retry=RetryPolicy(max_attempts=3, backoff_s=0.002,
                              seed=seed),
            breaker_failures=3, breaker_reset_s=0.5)
        tr.add_tenant(spec)

    # the table-sharing tenant must reuse the collided shape's ladder
    shared_pairs = [(a, b) for a in cfgs for b in cfgs
                    if cfgs[b].get("table_name") == a]
    ladder_shared = all(
        tr.router(a).buckets is tr.router(b).buckets
        for a, b in shared_pairs)

    # ---- scalar-oracle key pools (per tenant, per construction) ------
    pools = {}
    for name, cfg in cfgs.items():
        r = tr.router(name)
        pools[name] = {
            lb: _key_pool(r.server(lb), cfg["n"], distinct,
                          b"mt-%s-%s" % (name.encode(), lb.encode()))
            for lb in r.constructions}

    traces = {name: _mk_trace(cfg, seed + i, duration_s)
              for i, (name, cfg) in enumerate(cfgs.items())}

    gate_escapes = 0

    # ---- leg 1: solo baselines ---------------------------------------
    solo = {}
    for name in cfgs:
        tagged = _merge({name: traces[name]})
        lats, done, fails, sheds, mk = _replay_mt(tr, tagged, pools)
        esc = _escapes_by_tenant(done, pools)
        gate_escapes += sum(esc.values())
        solo[name] = _leg_stats({name: traces[name]}, lats, fails,
                                sheds, esc, slo_s)[name]
        solo[name]["makespan_s"] = round(mk, 4)

    # ---- leg 2: combined (all tenants concurrent) --------------------
    tagged = _merge(traces)
    lats, done, fails, sheds, mk = _replay_mt(tr, tagged, pools)
    esc = _escapes_by_tenant(done, pools)
    gate_escapes += sum(esc.values())
    combined = _leg_stats(traces, lats, fails, sheds, esc, slo_s)
    # honest qps: queries of ok batches / makespan
    ok_queries = sum(a.batch for name, a, j, fut in done)
    combined_qps = int(ok_queries / mk) if mk else 0
    combined_leg = {"per_tenant": combined,
                    "qps_ok": combined_qps,
                    "makespan_s": round(mk, 4)}

    # ---- leg 3: noisy-neighbor chaos ---------------------------------
    chaos_traces = dict(traces)
    chaos_traces[victim] = loadgen.squeeze(traces[victim], burst)
    tagged = _merge(chaos_traces)
    lats, done, fails, sheds, mk = _replay_mt(tr, tagged, pools,
                                              inject=True)
    esc = _escapes_by_tenant(done, pools)
    gate_escapes += sum(esc.values())
    chaos = _leg_stats(chaos_traces, lats, fails, sheds, esc, slo_s)
    injector = tr.router(victim).injector
    chaos_leg = {
        "victim": victim, "burst_factor": burst,
        "per_tenant": chaos,
        "makespan_s": round(mk, 4),
        "injected": injector.stats() if injector is not None else None,
    }

    # ---- isolation gate ----------------------------------------------
    isolation = {}
    for name in cfgs:
        if name == victim:
            continue
        solo_p99 = solo[name]["p99_ms"]
        chaos_p99 = chaos[name]["p99_ms"]
        ratio = (round(chaos_p99 / solo_p99, 4)
                 if solo_p99 and chaos_p99 is not None else None)
        p99_ok = (ratio is None or ratio <= TOLERANCE
                  or chaos_p99 - solo_p99 <= SLACK_MS)
        isolation[name] = {
            "availability": chaos[name]["availability"],
            "p99_solo_ms": solo_p99, "p99_chaos_ms": chaos_p99,
            "p99_vs_solo": ratio, "p99_slack_ms": SLACK_MS,
            "isolated": (chaos[name]["availability"] == 1.0
                         and chaos[name]["gate_escapes"] == 0
                         and p99_ok),
        }
    victim_degraded = (
        chaos[victim]["availability"] is not None
        and chaos[victim]["availability"] < 1.0)

    # ---- per-tenant observability visibility -------------------------
    # metrics snapshot series keys render labels as {a="x",tenant="y"};
    # a tenant is "visible" when some series carries its label
    obs = record_sections()
    metric_tenants = set()
    for fam in obs["metrics"].values():
        for labels in fam.get("series", {}):
            for name in cfgs:
                if 'tenant="%s"' % name in labels:
                    metric_tenants.add(name)
    flight_tenants = {e["tenant"] for e in FLIGHT.dump()
                      if "tenant" in e}
    per_tenant_series = {
        "metrics_tenants": sorted(metric_tenants),
        "flight_tenants": sorted(flight_tenants),
        "visible": all(n in metric_tenants for n in cfgs)
        and len(flight_tenants) > 0,
    }

    shapes = {(c["n"], c["e"]) for c in cfgs.values()
              if not c.get("table_name")}
    checked = (
        len(shapes) >= 3
        and gate_escapes == 0
        and all(i["isolated"] for i in isolation.values())
        and victim_degraded
        and ladder_shared
        and per_tenant_series["visible"]
    )

    tr.close()          # park the per-tenant dispatch workers
    record = {
        "metric": "multi-tenant serving isolation: %d tenants "
                  "(%d distinct (N,E) shapes + 1 table-sharing) under "
                  "one TenantRouter; noisy-neighbor chaos leg = %gx "
                  "victim burst + seeded fault plan (slo=%dms, 1 "
                  "device)"
                  % (len(cfgs), len(shapes), burst, int(slo_ms)),
        "value": combined_qps,
        "unit": "queries/sec",
        "slo_ms": slo_ms,
        "tenants": {name: {"n": cfg["n"], "entry_size": cfg["e"],
                           "cap": cfg["cap"], "on_rate": cfg["on_rate"],
                           "weight": cfg["weight"],
                           "table": cfg.get("table_name", name),
                           "victim": name == victim}
                    for name, cfg in cfgs.items()},
        "trace": {"kind": "bursty", "seed": seed,
                  "duration_s": duration_s},
        "solo": solo,
        "combined": combined_leg,
        "chaos": chaos_leg,
        "isolation": isolation,
        "victim_degraded": victim_degraded,
        "ladder_shared": ladder_shared,
        "per_tenant_series": per_tenant_series,
        "faults": {"plan": plan.as_dict()},
        "scheduler": tr.stats(),
        "gate_escapes": gate_escapes,
        "checked": bool(checked),
        "obs": obs,
    }
    if not checked:
        # a failed gate must be diagnosable: dump the full flight ring
        record["flight_on_gate_failure"] = FLIGHT.dump()
    if not quiet:
        print(json.dumps(record), flush=True)
    return record


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=16)
    ap.add_argument("--duration", type=float, default=5.0,
                    help="per-tenant trace duration in seconds")
    ap.add_argument("--slo-ms", type=float, default=400.0)
    ap.add_argument("--burst", type=float, default=4.0,
                    help="victim burst factor in the chaos leg")
    ap.add_argument("--prf", type=int, default=0,
                    help="PRF id (default 0=DUMMY; 2=ChaCha20, "
                         "3=AES128)")
    ap.add_argument("--dryrun", action="store_true",
                    help="tiny tables/traces smoke (CI): exercises "
                         "every leg in seconds, makes no perf claims")
    ap.add_argument("--out", help="also write the JSON record to a file")
    args = ap.parse_args(argv)
    record = multitenant_bench(
        seed=args.seed,
        duration_s=1.2 if args.dryrun else args.duration,
        slo_ms=args.slo_ms, burst=args.burst, prf=args.prf,
        distinct=6 if args.dryrun else 8, dryrun=args.dryrun)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    return record


if __name__ == "__main__":
    main()
