"""Deterministic open-loop arrival traces for the serving stack.

"Millions of users" is not a uniform stream of full batches: real query
traffic is bursty and heavy-tailed, and the batch size a burst actually
delivers is what decides which construction is fastest for it
(docs/SERVING.md "Load testing & SLOs").  This module generates the
traces everything traffic-shaped replays — the load harness
(``serve/bench_load.py``), the scheme router's rehearsals
(``serve/router.py``), and the serving-knob tuner
(``tune/serve_tune.py``, where the legacy ``synthetic_trace`` remains
the compatibility default):

* ``poisson_trace``  — memoryless arrivals at a constant rate (the
  open-loop baseline of the serving literature).
* ``bursty_trace``   — on/off (Markov-modulated) arrivals: ON windows
  at a high rate delivering near-cap batches, OFF windows a trickle of
  small stragglers.  The regime where a sticky scheme choice loses.
* ``diurnal_trace``  — a sinusoidal rate ramp (one "day" compressed to
  ``period_s``), peak-to-trough traffic swing.
* ``replay_trace``   — lift an explicit batch-size list (e.g. the
  legacy ``synthetic_trace`` output, or sizes scraped from a log) into
  timestamped arrivals.

Every generator is **open-loop** (arrival times are scheduled ahead of
time, independent of service progress — queues grow when the server
falls behind, exactly like real traffic) and **deterministic under its
seed**: the same (kind, seed, params) produce the identical trace on
every machine, so committed benchmark records are replayable and the
router/baseline race runs on byte-identical input.

An arrival is ``Arrival(t, n, batch)``: seconds since trace start, the
table domain the batch addresses (None = the harness's single table),
and the number of queries arriving together.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: trace kinds ``make_trace`` accepts
KINDS = ("poisson", "bursty", "diurnal", "replay")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One open-loop arrival: a batch of ``batch`` queries against
    domain ``n`` scheduled at ``t`` seconds after trace start."""
    t: float
    n: int | None
    batch: int


def batch_sizes(trace) -> list:
    """The batch-size view of a trace (timestamps dropped) — what the
    closed-loop serving-knob tuner replays (``tune_serving``), and the
    compatibility bridge from ``Arrival`` lists to code that predates
    them.  Accepts either a list of ``Arrival`` or a plain size list
    (returned as-is, ints)."""
    out = []
    for a in trace:
        out.append(int(a.batch) if isinstance(a, Arrival) else int(a))
    return out


def total_queries(trace) -> int:
    return sum(batch_sizes(trace))


def squeeze(trace, factor: float) -> list:
    """The same arrival sequence compressed in time by ``factor`` (> 1
    = hotter: identical batches delivered ``factor``x faster).  The
    overload leg of the load harness and the chaos bench both replay
    the SAME seeded trace squeezed, so "what changed" between legs is
    only the offered rate, never the batch mix."""
    if factor <= 0:
        raise ValueError("factor must be > 0 (got %r)" % (factor,))
    return [Arrival(a.t / factor, a.n, a.batch) for a in trace]


def scale_rate(trace, factor: float) -> list:
    """Alias of ``squeeze`` under the capacity planner's vocabulary:
    scale the OFFERED LOAD by ``factor`` (> 1 = hotter) by compressing
    arrival times, batches untouched.  The planner's headroom curves
    (``plan/capacity.plan_fleet``) sweep exactly this knob, so the
    name states the planning question ("what if traffic were 1.5x?")
    rather than the mechanism."""
    return squeeze(trace, factor)


def concat_traces(*traces, gap_s: float = 0.0) -> list:
    """Concatenate traces in time: each trace's arrivals are shifted
    to start ``gap_s`` seconds after the previous trace's LAST arrival
    (gap measured last-arrival -> first-arrival; an empty segment adds
    nothing).  Deterministic composition of deterministic pieces —
    ``concat_traces(day, day)`` is the two-day diurnal input the
    capacity planner sweeps, same seed, same composed trace on every
    machine.  Like ``squeeze``/``scale_rate``, the batch mix is
    untouched: only the timeline changes."""
    if gap_s < 0:
        raise ValueError("gap_s must be >= 0 (got %r)" % (gap_s,))
    out = []
    offset = 0.0
    for tr in traces:
        if not tr:
            continue
        base = offset - tr[0].t
        out.extend(Arrival(base + a.t, a.n, a.batch) for a in tr)
        offset = out[-1].t + gap_s
    return out


def bucket_rates(trace, buckets, *, duration_s: float | None = None) -> dict:
    """Per-bucket arrival rates of a trace: ``{bucket_size: dispatches
    per second}`` over the trace duration, with a zero entry for every
    rung of the ladder (full coverage — consumers can iterate the dict
    without guarding missing rungs).

    ``buckets`` is a ``serve.buckets.Buckets`` or a plain size list.
    Each arrival is counted the way the engine would dispatch it: a
    batch above the cap is split with ``Buckets.chunks`` and every span
    lands in its own bucket, so the rates describe *dispatch* pressure
    per compiled shape, not raw arrival counts.  ``duration_s`` defaults
    to the last arrival's timestamp (1.0 s floor, so a burst at t=0
    still yields finite rates).

    Deterministic: a pure function of (trace, buckets) — the offline
    twin of ``SchemeRouter.arrival_rates`` (the EWMA live estimator the
    ``GranulePrefetcher`` consumes), and the trace summary
    ``tune_router`` records next to its tuned ladder."""
    from .buckets import Buckets
    bk = buckets if isinstance(buckets, Buckets) else Buckets(buckets)
    counts = {s: 0 for s in bk.sizes}
    t_last = 0.0
    for a in trace:
        if isinstance(a, Arrival):
            t_last = max(t_last, a.t)
            b = a.batch
        else:
            b = int(a)
        for lo, hi in bk.chunks(b):
            counts[bk.bucket_for(hi - lo)] += 1
    if duration_s is None:
        duration_s = max(t_last, 1.0)
    if duration_s <= 0:
        raise ValueError("duration_s must be > 0 (got %r)" % (duration_s,))
    return {s: c / duration_s for s, c in counts.items()}


def _draw_batch(rng, lo: int, hi: int) -> int:
    """Log-uniform batch size in [lo, hi]: small batches must be common
    enough to exercise the lower ladder rungs, big ones common enough
    to load the device — a uniform draw would almost never produce a
    size-1 straggler at cap=512."""
    lo, hi = max(1, int(lo)), max(1, int(hi))
    if lo >= hi:
        return hi
    b = np.exp(rng.uniform(np.log(lo), np.log(hi + 1)))
    return int(np.clip(np.round(b), lo, hi))


def poisson_trace(*, rate: float, duration_s: float | None = None,
                  arrivals: int | None = None, cap: int = 512,
                  min_batch: int = 1, n: int | None = None,
                  seed: int = 0) -> list:
    """Memoryless arrivals: exponential inter-arrival gaps at ``rate``
    per second, batch sizes log-uniform in [min_batch, cap].  Stop
    after ``duration_s`` seconds or ``arrivals`` arrivals (exactly one
    must be given)."""
    if (duration_s is None) == (arrivals is None):
        raise ValueError("give exactly one of duration_s / arrivals")
    if rate <= 0:
        raise ValueError("rate must be > 0 (got %r)" % (rate,))
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    while True:
        t += rng.exponential(1.0 / rate)
        if duration_s is not None and t >= duration_s:
            break
        out.append(Arrival(t, n, _draw_batch(rng, min_batch, cap)))
        if arrivals is not None and len(out) >= arrivals:
            break
    return out


def bursty_trace(*, on_rate: float, off_rate: float, on_s: float,
                 off_s: float, duration_s: float, cap: int = 512,
                 n: int | None = None, seed: int = 0) -> list:
    """On/off (two-state Markov-modulated) Poisson arrivals.

    ON windows of ``on_s`` seconds fire at ``on_rate``/s with batch
    sizes concentrated near ``cap`` (the loaded-burst regime: cap or
    cap/2, occasionally smaller); OFF windows of ``off_s`` seconds
    trickle at ``off_rate``/s with small straggler batches (log-uniform
    in [1, cap/8]).  This is the mixed-shape traffic where the fastest
    construction per delivered batch size changes mid-trace — the
    router's target workload."""
    if on_rate <= 0 or off_rate <= 0:
        raise ValueError("rates must be > 0")
    if on_s <= 0 or off_s <= 0:
        raise ValueError("window lengths must be > 0")
    rng = np.random.default_rng(seed)
    out, t0, on = [], 0.0, True
    while t0 < duration_s:
        # simulate each window at its own rate: the inter-arrival clock
        # restarts at every state switch, so a long OFF gap cannot leap
        # over (and silence) the ON windows behind it
        window = on_s if on else off_s
        end = min(t0 + window, duration_s)
        rate = on_rate if on else off_rate
        t = t0
        while True:
            t += rng.exponential(1.0 / rate)
            if t >= end:
                break
            out.append(Arrival(t, n, _bursty_batch(rng, on, cap)))
        t0, on = t0 + window, not on
    return out


def _bursty_batch(rng, on: bool, cap: int) -> int:
    if on:
        r = rng.random()
        if r < 0.6:
            return cap
        if r < 0.9:
            return max(1, cap // 2)
        return _draw_batch(rng, max(1, cap // 4), cap)
    return _draw_batch(rng, 1, max(1, cap // 8))


def diurnal_trace(*, base_rate: float, peak_rate: float,
                  period_s: float, duration_s: float, cap: int = 512,
                  n: int | None = None, seed: int = 0) -> list:
    """A sinusoidal rate ramp — one traffic "day" compressed into
    ``period_s`` seconds, rate swinging base → peak → base.  Arrivals
    are drawn by thinning a Poisson stream at ``peak_rate`` (the exact
    inhomogeneous-Poisson recipe), so the realized rate tracks the
    ramp; batch sizes scale with the instantaneous load (near-cap at
    peak, small at trough)."""
    if not 0 < base_rate <= peak_rate:
        raise ValueError("need 0 < base_rate <= peak_rate")
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    while True:
        t += rng.exponential(1.0 / peak_rate)
        if t >= duration_s:
            break
        phase = (1 - np.cos(2 * np.pi * t / period_s)) / 2   # 0..1..0
        rate = base_rate + (peak_rate - base_rate) * phase
        if rng.random() > rate / peak_rate:
            continue                      # thinned out
        hi = max(1, int(round(cap * max(phase, 1.0 / cap))))
        out.append(Arrival(t, n, _draw_batch(rng, 1, hi)))
    return out


def replay_trace(sizes, *, rate: float | None = None,
                 n: int | None = None) -> list:
    """Lift an explicit batch-size list into arrivals: uniform gaps of
    ``1/rate`` seconds (``rate=None`` = all at t=0, i.e. a closed-loop
    back-to-back replay — the legacy tuner behavior)."""
    gap = 0.0 if rate is None else 1.0 / rate
    return [Arrival(i * gap, n, int(b)) for i, b in enumerate(sizes)]


def make_trace(kind: str, **kw) -> list:
    """Dispatch by trace kind ("poisson" / "bursty" / "diurnal" /
    "replay") — the string spelling the CLI and the tuner use."""
    if kind == "poisson":
        return poisson_trace(**kw)
    if kind == "bursty":
        return bursty_trace(**kw)
    if kind == "diurnal":
        return diurnal_trace(**kw)
    if kind == "replay":
        return replay_trace(**kw)
    raise ValueError("unknown trace kind %r (one of %s)"
                     % (kind, ", ".join(KINDS)))


def default_trace(kind: str, cap: int, *, seed: int = 7,
                  duration_s: float = 4.0) -> list:
    """A canonical small trace per kind — what the serving-knob tuner
    replays when handed just a ``trace_kind`` string (its parameters
    then come from here, not the caller), and what tests use for a
    deterministic non-trivial trace without repeating rate math."""
    if kind == "poisson":
        return poisson_trace(rate=30.0, duration_s=duration_s, cap=cap,
                             seed=seed)
    if kind == "bursty":
        return default_bursty(cap, seed=seed, duration_s=duration_s)
    if kind == "diurnal":
        return diurnal_trace(base_rate=4.0, peak_rate=40.0,
                             period_s=duration_s / 2,
                             duration_s=duration_s, cap=cap, seed=seed)
    raise ValueError("no default trace for kind %r (one of poisson, "
                     "bursty, diurnal)" % (kind,))


def default_bursty(cap: int, *, seed: int = 11,
                   duration_s: float = 8.0) -> list:
    """A canonical moderate bursty trace (1 s bursts at 40/s every
    3 s, a 2/s straggler trickle in between) — what
    ``default_trace("bursty")`` hands the serving-knob tuner and what
    tests use for a deterministic mixed-shape workload.  The load
    harness's committed record uses its own, hotter parameters
    (``bench_load.load_bench``: the burst rate there is calibrated to
    overload the sticky construction, and is recorded in the
    ``trace`` field of BENCH_LOAD_r10.json)."""
    return bursty_trace(on_rate=40.0, off_rate=2.0, on_s=1.0, off_s=2.0,
                        duration_s=duration_s, cap=cap, seed=seed)
