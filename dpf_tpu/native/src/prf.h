// Native PRFs over unsigned __int128 — bit-exact with the framework's
// Python/JAX implementations (semantics per the reference,
// dpf_base/dpf.h:65-235): DUMMY, Salsa20-12, ChaCha20-12, AES-128.
// AES uses AES-NI when the CPU supports it, with a portable fallback.
#pragma once

#include <cstdint>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#include <wmmintrin.h>
#endif

namespace dpftpu {

typedef unsigned __int128 u128;

enum PrfMethod {
  kDummy = 0,
  kSalsa20 = 1,
  kChaCha20 = 2,
  kAes128 = 3,
  // Block-PRG ("wide") variants: child pos = 128-bit word group
  // pos%4 of the 512-bit core block at counter pos/4 — one core call
  // serves four GGM children (core/prf_ref.py prf_salsa20_12_blk).
  kSalsa20Blk = 4,
  kChaCha20Blk = 5,
};

inline u128 prf_dummy(u128 seed, u128 pos) {
  u128 t = pos + 4242;
  return seed * t + t;
}

namespace detail {

inline uint32_t rotl32(uint32_t v, int s) { return (v << s) | (v >> (32 - s)); }

constexpr uint32_t kSigma[4] = {0x65787061u, 0x6e642033u, 0x322d6279u,
                                0x7465206bu};

}  // namespace detail

// 12-round Salsa20 full block; 128-bit key in state words 1..4 (MSW
// first), 64-bit counter in words 8..9 (high word first).
inline void salsa20_12_block(u128 seed, u128 ctr, uint32_t out[16]) {
  using detail::rotl32;
  uint32_t in[16] = {0}, x[16];
  in[0] = detail::kSigma[0];
  in[5] = detail::kSigma[1];
  in[10] = detail::kSigma[2];
  in[15] = detail::kSigma[3];
  in[1] = static_cast<uint32_t>(seed >> 96);
  in[2] = static_cast<uint32_t>(seed >> 64);
  in[3] = static_cast<uint32_t>(seed >> 32);
  in[4] = static_cast<uint32_t>(seed);
  in[8] = static_cast<uint32_t>(ctr >> 32);
  in[9] = static_cast<uint32_t>(ctr);
  std::memcpy(x, in, sizeof(x));
#define DPFTPU_SALSA_QR(a, b, c, d)   \
  x[b] ^= rotl32(x[a] + x[d], 7);     \
  x[c] ^= rotl32(x[b] + x[a], 9);     \
  x[d] ^= rotl32(x[c] + x[b], 13);    \
  x[a] ^= rotl32(x[d] + x[c], 18);
  for (int r = 0; r < 6; r++) {
    DPFTPU_SALSA_QR(0, 4, 8, 12)
    DPFTPU_SALSA_QR(5, 9, 13, 1)
    DPFTPU_SALSA_QR(10, 14, 2, 6)
    DPFTPU_SALSA_QR(15, 3, 7, 11)
    DPFTPU_SALSA_QR(0, 1, 2, 3)
    DPFTPU_SALSA_QR(5, 6, 7, 4)
    DPFTPU_SALSA_QR(10, 11, 8, 9)
    DPFTPU_SALSA_QR(15, 12, 13, 14)
  }
#undef DPFTPU_SALSA_QR
  for (int i = 0; i < 16; i++) out[i] = x[i] + in[i];
}

inline u128 prf_salsa20_12(u128 seed, u128 pos) {
  uint32_t o[16];
  salsa20_12_block(seed, pos, o);
  return (static_cast<u128>(o[1]) << 96) | (static_cast<u128>(o[2]) << 64) |
         (static_cast<u128>(o[3]) << 32) | static_cast<u128>(o[4]);
}

// 12-round ChaCha full block; key in words 4..7 (MSW first), 64-bit
// counter in words 12..13 (high word first).
inline void chacha20_12_block(u128 seed, u128 ctr, uint32_t out[16]) {
  using detail::rotl32;
  uint32_t in[16] = {0}, x[16];
  for (int i = 0; i < 4; i++) in[i] = detail::kSigma[i];
  in[4] = static_cast<uint32_t>(seed >> 96);
  in[5] = static_cast<uint32_t>(seed >> 64);
  in[6] = static_cast<uint32_t>(seed >> 32);
  in[7] = static_cast<uint32_t>(seed);
  in[12] = static_cast<uint32_t>(ctr >> 32);
  in[13] = static_cast<uint32_t>(ctr);
  std::memcpy(x, in, sizeof(x));
#define DPFTPU_CHACHA_QR(a, b, c, d)      \
  x[a] += x[b]; x[d] = rotl32(x[d] ^ x[a], 16); \
  x[c] += x[d]; x[b] = rotl32(x[b] ^ x[c], 12); \
  x[a] += x[b]; x[d] = rotl32(x[d] ^ x[a], 8);  \
  x[c] += x[d]; x[b] = rotl32(x[b] ^ x[c], 7);
  for (int r = 0; r < 6; r++) {
    DPFTPU_CHACHA_QR(0, 4, 8, 12)
    DPFTPU_CHACHA_QR(1, 5, 9, 13)
    DPFTPU_CHACHA_QR(2, 6, 10, 14)
    DPFTPU_CHACHA_QR(3, 7, 11, 15)
    DPFTPU_CHACHA_QR(0, 5, 10, 15)
    DPFTPU_CHACHA_QR(1, 6, 11, 12)
    DPFTPU_CHACHA_QR(2, 7, 8, 13)
    DPFTPU_CHACHA_QR(3, 4, 9, 14)
  }
#undef DPFTPU_CHACHA_QR
  for (int i = 0; i < 16; i++) out[i] = x[i] + in[i];
}

inline u128 prf_chacha20_12(u128 seed, u128 pos) {
  uint32_t o[16];
  chacha20_12_block(seed, pos, o);
  return (static_cast<u128>(o[4]) << 96) | (static_cast<u128>(o[5]) << 64) |
         (static_cast<u128>(o[6]) << 32) | static_cast<u128>(o[7]);
}

// Block-PRG variants (see PrfMethod): group pos%4 of block at counter pos/4.
inline u128 blk_child(const uint32_t o[16], u128 pos) {
  int g = 4 * static_cast<int>(pos & 3);
  return (static_cast<u128>(o[g]) << 96) |
         (static_cast<u128>(o[g + 1]) << 64) |
         (static_cast<u128>(o[g + 2]) << 32) | static_cast<u128>(o[g + 3]);
}

inline u128 prf_salsa20_12_blk(u128 seed, u128 pos) {
  uint32_t o[16];
  salsa20_12_block(seed, pos >> 2, o);
  return blk_child(o, pos);
}

inline u128 prf_chacha20_12_blk(u128 seed, u128 pos) {
  uint32_t o[16];
  chacha20_12_block(seed, pos >> 2, o);
  return blk_child(o, pos);
}

// ---------------------------------------------------------------------------
// AES-128 (FIPS-197): key = 16 LE bytes of seed, pt = 16 LE bytes of pos.
// ---------------------------------------------------------------------------

namespace detail {

struct AesTables {
  uint8_t sbox[256];
  AesTables() {
    // generate S-box from the GF(2^8) inverse + affine transform
    uint8_t p = 1, q = 1;
    do {
      p = static_cast<uint8_t>(p ^ (p << 1) ^ ((p & 0x80) ? 0x1B : 0));
      q ^= static_cast<uint8_t>(q << 1);
      q ^= static_cast<uint8_t>(q << 2);
      q ^= static_cast<uint8_t>(q << 4);
      if (q & 0x80) q ^= 0x09;
      sbox[p] = static_cast<uint8_t>(q ^ rotl8(q, 1) ^ rotl8(q, 2) ^
                                     rotl8(q, 3) ^ rotl8(q, 4) ^ 0x63);
    } while (p != 1);
    sbox[0] = 0x63;
  }
  static uint8_t rotl8(uint8_t v, int s) {
    return static_cast<uint8_t>((v << s) | (v >> (8 - s)));
  }
};

inline const AesTables& aes_tables() {
  static AesTables t;
  return t;
}

inline uint8_t xtime(uint8_t b) {
  return static_cast<uint8_t>((b << 1) ^ ((b & 0x80) ? 0x1B : 0));
}

inline void aes128_portable(const uint8_t key[16], const uint8_t in[16],
                            uint8_t out[16]) {
  const uint8_t* S = aes_tables().sbox;
  uint8_t rk[16], st[16];
  std::memcpy(rk, key, 16);
  for (int i = 0; i < 16; i++) st[i] = in[i] ^ rk[i];
  uint8_t rcon = 1;
  for (int round = 1; round <= 10; round++) {
    uint8_t tmp[16];
    // SubBytes + ShiftRows fused: out byte 4c+r <- S[st[4((c+r)%4)+r]]
    for (int c = 0; c < 4; c++)
      for (int r = 0; r < 4; r++)
        tmp[4 * c + r] = S[st[4 * ((c + r) % 4) + r]];
    if (round < 10) {
      for (int c = 0; c < 4; c++) {
        uint8_t* a = tmp + 4 * c;
        uint8_t t = a[0] ^ a[1] ^ a[2] ^ a[3];
        uint8_t a0 = a[0];
        a[0] = static_cast<uint8_t>(a[0] ^ t ^ xtime(a[0] ^ a[1]));
        a[1] = static_cast<uint8_t>(a[1] ^ t ^ xtime(a[1] ^ a[2]));
        a[2] = static_cast<uint8_t>(a[2] ^ t ^ xtime(a[2] ^ a[3]));
        a[3] = static_cast<uint8_t>(a[3] ^ t ^ xtime(a[3] ^ a0));
      }
    }
    // next round key (fused schedule)
    uint8_t w[4] = {S[rk[13]], S[rk[14]], S[rk[15]], S[rk[12]]};
    w[0] ^= rcon;
    rcon = xtime(rcon);
    for (int i = 0; i < 4; i++) rk[i] ^= w[i];
    for (int i = 4; i < 16; i++) rk[i] ^= rk[i - 4];
    for (int i = 0; i < 16; i++) st[i] = tmp[i] ^ rk[i];
  }
  std::memcpy(out, st, 16);
}

#if defined(__x86_64__) && defined(__AES__)
template <int R>
inline __m128i aes_expand_step(__m128i k) {
  __m128i t = _mm_aeskeygenassist_si128(k, R);
  t = _mm_shuffle_epi32(t, 0xFF);
  k = _mm_xor_si128(k, _mm_slli_si128(k, 4));
  k = _mm_xor_si128(k, _mm_slli_si128(k, 4));
  k = _mm_xor_si128(k, _mm_slli_si128(k, 4));
  return _mm_xor_si128(k, t);
}

inline void aes128_ni(const uint8_t key[16], const uint8_t in[16],
                      uint8_t out[16]) {
  __m128i k[11];
  k[0] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(key));
  k[1] = aes_expand_step<0x01>(k[0]);
  k[2] = aes_expand_step<0x02>(k[1]);
  k[3] = aes_expand_step<0x04>(k[2]);
  k[4] = aes_expand_step<0x08>(k[3]);
  k[5] = aes_expand_step<0x10>(k[4]);
  k[6] = aes_expand_step<0x20>(k[5]);
  k[7] = aes_expand_step<0x40>(k[6]);
  k[8] = aes_expand_step<0x80>(k[7]);
  k[9] = aes_expand_step<0x1B>(k[8]);
  k[10] = aes_expand_step<0x36>(k[9]);
  __m128i st = _mm_loadu_si128(reinterpret_cast<const __m128i*>(in));
  st = _mm_xor_si128(st, k[0]);
  for (int r = 1; r < 10; r++) st = _mm_aesenc_si128(st, k[r]);
  st = _mm_aesenclast_si128(st, k[10]);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(out), st);
}
#endif

}  // namespace detail

inline u128 prf_aes128(u128 seed, u128 pos) {
  uint8_t key[16], in[16], out[16];
  std::memcpy(key, &seed, 16);  // little-endian host
  std::memcpy(in, &pos, 16);
#if defined(__x86_64__) && defined(__AES__)
  static const bool has_ni = __builtin_cpu_supports("aes");
  if (has_ni)
    detail::aes128_ni(key, in, out);
  else
    detail::aes128_portable(key, in, out);
#else
  detail::aes128_portable(key, in, out);
#endif
  u128 r;
  std::memcpy(&r, out, 16);
  return r;
}

inline u128 prf(int method, u128 seed, u128 pos) {
  switch (method) {
    case kDummy: return prf_dummy(seed, pos);
    case kSalsa20: return prf_salsa20_12(seed, pos);
    case kChaCha20: return prf_chacha20_12(seed, pos);
    case kAes128: return prf_aes128(seed, pos);
    case kSalsa20Blk: return prf_salsa20_12_blk(seed, pos);
    case kChaCha20Blk: return prf_chacha20_12_blk(seed, pos);
  }
  return 0;
}

}  // namespace dpftpu
