// SHAKE-256 XOF (FIPS-202), self-contained implementation for the native
// keygen DRBG.  Must produce byte-identical streams to Python's
// hashlib.shake_256 so native and Python keygen agree key-for-key
// (dpf_tpu/core/keygen.py Shake256Drbg).
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

namespace dpftpu {

class Keccak1600 {
 public:
  static constexpr int kRounds = 24;

  static void permute(uint64_t st[25]) {
    static const uint64_t RC[kRounds] = {
        0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
        0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
        0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
        0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
        0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
        0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
        0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
        0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};
    static const int rho[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10,
                                43, 25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56,
                                14};
    for (int round = 0; round < kRounds; round++) {
      // theta
      uint64_t C[5], D[5];
      for (int x = 0; x < 5; x++)
        C[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
      for (int x = 0; x < 5; x++) {
        D[x] = C[(x + 4) % 5] ^ rotl(C[(x + 1) % 5], 1);
        for (int y = 0; y < 5; y++) st[x + 5 * y] ^= D[x];
      }
      // rho + pi
      uint64_t B[25];
      for (int x = 0; x < 5; x++)
        for (int y = 0; y < 5; y++)
          B[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(st[x + 5 * y],
                                                  rho[x + 5 * y]);
      // chi
      for (int x = 0; x < 5; x++)
        for (int y = 0; y < 5; y++)
          st[x + 5 * y] = B[x + 5 * y] ^
                          ((~B[(x + 1) % 5 + 5 * y]) & B[(x + 2) % 5 + 5 * y]);
      // iota
      st[0] ^= RC[round];
    }
  }

 private:
  static inline uint64_t rotl(uint64_t v, int s) {
    return s == 0 ? v : (v << s) | (v >> (64 - s));
  }
};

// One-shot SHAKE-256: absorb `in`, squeeze `outlen` bytes.
inline void shake256(const uint8_t* in, size_t inlen, uint8_t* out,
                     size_t outlen) {
  constexpr size_t rate = 136;  // SHAKE-256 rate in bytes
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  // absorb
  size_t off = 0;
  while (inlen - off >= rate) {
    for (size_t i = 0; i < rate; i++)
      reinterpret_cast<uint8_t*>(st)[i] ^= in[off + i];
    Keccak1600::permute(st);
    off += rate;
  }
  // final partial block + padding (0x1F ... 0x80)
  uint8_t* stb = reinterpret_cast<uint8_t*>(st);
  for (size_t i = 0; i < inlen - off; i++) stb[i] ^= in[off + i];
  stb[inlen - off] ^= 0x1F;
  stb[rate - 1] ^= 0x80;
  Keccak1600::permute(st);
  // squeeze
  size_t produced = 0;
  while (produced < outlen) {
    size_t take = std::min(rate, outlen - produced);
    std::memcpy(out + produced, st, take);
    produced += take;
    if (produced < outlen) Keccak1600::permute(st);
  }
}

// Deterministic DRBG matching Python's Shake256Drbg: the stream is the
// concatenation of SHAKE-256(seed || ctr_le64)[0:1024] blocks.
class Shake256Drbg {
 public:
  Shake256Drbg(const uint8_t* seed, size_t seed_len)
      : seed_(seed, seed + seed_len), ctr_(0), pos_(0) {}

  void bytes(uint8_t* out, size_t n) {
    size_t got = 0;
    while (got < n) {
      if (pos_ == buf_.size()) refill();
      size_t take = std::min(n - got, buf_.size() - pos_);
      std::memcpy(out + got, buf_.data() + pos_, take);
      pos_ += take;
      got += take;
    }
  }

  unsigned __int128 u128() {
    uint8_t b[16];
    bytes(b, 16);
    unsigned __int128 v = 0;
    for (int i = 15; i >= 0; i--) v = (v << 8) | b[i];  // little-endian
    return v;
  }

  unsigned __int128 u128_odd() { return u128() | 1; }

 private:
  void refill() {
    std::vector<uint8_t> msg(seed_);
    for (int i = 0; i < 8; i++)
      msg.push_back(static_cast<uint8_t>((ctr_ >> (8 * i)) & 0xFF));
    ctr_++;
    buf_.assign(1024, 0);
    shake256(msg.data(), msg.size(), buf_.data(), buf_.size());
    pos_ = 0;
  }

  std::vector<uint8_t> seed_;
  uint64_t ctr_;
  std::vector<uint8_t> buf_;
  size_t pos_;
};

}  // namespace dpftpu
