// Native DPF runtime: keygen (GGM log-N construction), flat evaluation,
// and full breadth-first expansion.  C ABI for ctypes.
//
// Mirrors the capabilities of the reference's C++ core (dpf_base/dpf.h)
// with this framework's own iterative construction (seed-LSB control bit,
// identical wire format: 524 int32 = depth | cw1[64] | cw2[64] | last | n)
// and a SHAKE-256 DRBG byte-identical to the Python keygen, so both paths
// produce the same keys for the same seed.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include "prf.h"
#include "shake256.h"

namespace dpftpu {

constexpr int kKeyWords = 524;

namespace {

struct FlatKey {
  int depth;
  u128 cw1[64];
  u128 cw2[64];
  u128 last_key;
  uint64_t n;
};

void serialize(const FlatKey& k, int32_t* out) {
  u128* slots = reinterpret_cast<u128*>(out);
  std::memset(out, 0, kKeyWords * sizeof(int32_t));
  slots[0] = static_cast<u128>(k.depth);
  std::memcpy(&slots[1], k.cw1, sizeof(k.cw1));
  std::memcpy(&slots[65], k.cw2, sizeof(k.cw2));
  slots[129] = k.last_key;
  slots[130] = static_cast<u128>(k.n);
}

void deserialize(const int32_t* in, FlatKey* k) {
  const u128* slots = reinterpret_cast<const u128*>(in);
  k->depth = static_cast<int>(slots[0]);
  std::memcpy(k->cw1, &slots[1], sizeof(k->cw1));
  std::memcpy(k->cw2, &slots[65], sizeof(k->cw2));
  k->last_key = slots[129];
  k->n = static_cast<uint64_t>(slots[130]);
}

// Iterative GGM construction, base level (alpha bit 0) up to the root.
// Draw order matches dpf_tpu.core.keygen.generate_keys exactly.
int generate(uint64_t alpha, uint64_t n, const uint8_t* seed, size_t seed_len,
             int prf_method, u128 beta, FlatKey* k0, FlatKey* k1) {
  if (n < 2 || (n & (n - 1)) != 0 || alpha >= n) return -1;
  int depth = 0;
  for (uint64_t v = n; v > 1; v >>= 1) depth++;
  if (depth > 32) return -1;

  Shake256Drbg rng(seed, seed_len);
  std::memset(k0, 0, sizeof(FlatKey));
  std::memset(k1, 0, sizeof(FlatKey));
  k0->depth = k1->depth = depth;
  k0->n = k1->n = n;

  // base level
  u128 ka = rng.u128() & ~static_cast<u128>(1);
  u128 kb = rng.u128() | 1;
  k0->last_key = ka;
  k1->last_key = kb;
  u128 beta_l = (depth == 1) ? beta : rng.u128_odd();
  int i = depth - 1;
  int bit0 = static_cast<int>(alpha & 1);
  u128 c1[2] = {rng.u128(), rng.u128()};
  for (int b = 0; b < 2; b++) {
    u128 d = prf(prf_method, ka, b) - prf(prf_method, kb, b);
    if (b == bit0) d -= beta_l;
    k0->cw1[2 * i + b] = k1->cw1[2 * i + b] = c1[b];
    k0->cw2[2 * i + b] = k1->cw2[2 * i + b] = c1[b] + d;
  }
  u128 s1 = prf(prf_method, ka, bit0) + c1[bit0];
  u128 s2 = prf(prf_method, kb, bit0) + k0->cw2[2 * i + bit0];

  // upper levels
  for (int l = 1; l < depth; l++) {
    i = depth - 1 - l;
    beta_l = (l == depth - 1) ? beta : rng.u128_odd();
    int tb = static_cast<int>((alpha >> l) & 1);
    bool s1_even = (s1 & 1) == 0;
    u128 cc[2] = {rng.u128(), rng.u128()};
    for (int b = 0; b < 2; b++) {
      u128 d = prf(prf_method, s2, b) - prf(prf_method, s1, b);
      if (s1_even) d = -d;
      k0->cw2[2 * i + b] = k1->cw2[2 * i + b] = cc[b] + d;
    }
    cc[tb] += s1_even ? beta_l : -beta_l;
    for (int b = 0; b < 2; b++)
      k0->cw1[2 * i + b] = k1->cw1[2 * i + b] = cc[b];
    u128 cw2t = k0->cw2[2 * i + tb];
    u128 n1 = prf(prf_method, s1, tb) + (s1_even ? cc[tb] : cw2t);
    u128 n2 = prf(prf_method, s2, tb) + (s1_even ? cw2t : cc[tb]);
    s1 = n1;
    s2 = n2;
  }
  return 0;
}

u128 eval_point(const FlatKey& k, uint64_t indx, int prf_method) {
  u128 cur = k.last_key;
  uint64_t rem = indx;
  for (int i = k.depth - 1; i >= 0; i--) {
    int b = static_cast<int>(rem & 1);
    u128 val = prf(prf_method, cur, b);
    const u128* cw = ((cur & 1) == 0) ? k.cw1 : k.cw2;
    cur = val + cw[2 * i + b];
    rem >>= 1;
  }
  return cur;
}

// Full breadth-first expansion; out[j] = low 32 bits of the leaf for
// natural index j (bit-reversal applied on store).
int expand_all(const FlatKey& k, int prf_method, int32_t* out) {
  uint64_t n = k.n;
  std::vector<u128> cur(1, k.last_key), next;
  uint64_t width = 1;
  for (int i = k.depth - 1; i >= 0; i--) {
    next.resize(width * 2);
    for (uint64_t j = 0; j < width; j++) {
      u128 s = cur[j];
      const u128* cw = ((s & 1) == 0) ? k.cw1 : k.cw2;
      next[2 * j] = prf(prf_method, s, 0) + cw[2 * i];
      next[2 * j + 1] = prf(prf_method, s, 1) + cw[2 * i + 1];
    }
    cur.swap(next);
    width *= 2;
  }
  // natural[j] = bfs[bit_reverse(j)]; equivalently scatter bfs[p] to
  // natural[bit_reverse(p)]
  int bits = k.depth;
  for (uint64_t p = 0; p < n; p++) {
    uint64_t r = 0;
    for (int b = 0; b < bits; b++) r |= ((p >> b) & 1) << (bits - 1 - b);
    out[r] = static_cast<int32_t>(static_cast<uint32_t>(cur[p]));
  }
  return 0;
}

}  // namespace
}  // namespace dpftpu

extern "C" {

int dpftpu_gen(uint64_t alpha, uint64_t n, const uint8_t* seed,
               uint64_t seed_len, int prf_method, int32_t* key0_out,
               int32_t* key1_out) {
  dpftpu::FlatKey k0, k1;
  int rc = dpftpu::generate(alpha, n, seed, seed_len, prf_method, 1, &k0, &k1);
  if (rc != 0) return rc;
  dpftpu::serialize(k0, key0_out);
  dpftpu::serialize(k1, key1_out);
  return 0;
}

// out must hold n int32 (natural index order, low-32 truncated shares).
int dpftpu_eval_expand(const int32_t* key, int prf_method, int32_t* out) {
  dpftpu::FlatKey k;
  dpftpu::deserialize(key, &k);
  if (k.depth < 1 || k.depth > 32) return -1;
  return dpftpu::expand_all(k, prf_method, out);
}

// out4: little-endian uint32 limbs of the full 128-bit share at indx.
int dpftpu_eval_point(const int32_t* key, uint64_t indx, int prf_method,
                      uint32_t* out4) {
  dpftpu::FlatKey k;
  dpftpu::deserialize(key, &k);
  if (k.depth < 1 || k.depth > 32) return -1;
  dpftpu::u128 v = dpftpu::eval_point(k, indx, prf_method);
  for (int i = 0; i < 4; i++)
    out4[i] = static_cast<uint32_t>(v >> (32 * i));
  return 0;
}

// Batched expansion with fused mod-2^32 contraction against a table:
// keys is batch x 524 int32 (contiguous); table is [n x entry_size] int32
// in natural row order; out is [batch x entry_size] int32.  Runs the batch
// across `n_threads` std::threads — the CPU-baseline analogue of the
// reference's OpenMP harness (paper/kernel/cpu/dpf_google/benchmark.cu),
// used for the CPU-vs-TPU speedup tables.
int dpftpu_eval_contract(const int32_t* keys, uint64_t batch, int prf_method,
                         const int32_t* table, uint64_t entry_size,
                         int n_threads, int32_t* out) {
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> workers;
  std::atomic<int> rc{0};
  auto work = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t b = lo; b < hi; b++) {
      dpftpu::FlatKey k;
      dpftpu::deserialize(keys + b * dpftpu::kKeyWords, &k);
      if (k.depth < 1 || k.depth > 32) {
        rc.store(-1, std::memory_order_relaxed);
        return;
      }
      std::vector<int32_t> hot(k.n);
      dpftpu::expand_all(k, prf_method, hot.data());
      for (uint64_t e = 0; e < entry_size; e++) {
        uint32_t acc = 0;
        for (uint64_t j = 0; j < k.n; j++)
          acc += static_cast<uint32_t>(hot[j]) *
                 static_cast<uint32_t>(table[j * entry_size + e]);
        out[b * entry_size + e] = static_cast<int32_t>(acc);
      }
    }
  };
  uint64_t per = (batch + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    uint64_t lo = t * per, hi = std::min(batch, (t + 1) * per);
    if (lo >= hi) break;
    workers.emplace_back(work, lo, hi);
  }
  for (auto& w : workers) w.join();
  return rc.load();
}

int dpftpu_key_words(void) { return dpftpu::kKeyWords; }
}
