"""ctypes loader for the native DPF runtime (builds on demand, falls back).

The native library accelerates the host-side paths (keygen, eval_cpu) the
way the reference's C++ core does (``dpf_base/dpf.h``); the TPU path never
needs it.  If no compiler is available the pure-Python implementations are
used transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_THIS = os.path.dirname(__file__)
_SRC = os.path.join(_THIS, "src", "dpftpu.cpp")
_LIB = os.path.join(_THIS, "libdpftpu.so")

_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-pthread", "-o", _LIB, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        # -march=native may be unavailable in exotic setups; retry plain
        try:
            subprocess.run([c for c in cmd if c != "-march=native"],
                           check=True, capture_output=True)
            return True
        except (subprocess.CalledProcessError, FileNotFoundError):
            return False


def load():
    """Returns the ctypes library handle, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src_dir = os.path.join(_THIS, "src")
    try:
        newest_src = max(os.path.getmtime(os.path.join(src_dir, f))
                         for f in os.listdir(src_dir))
    except (OSError, ValueError):
        newest_src = None  # no sources shipped: use a prebuilt lib as-is
    if not os.path.exists(_LIB) or (newest_src is not None
                                    and os.path.getmtime(_LIB) < newest_src):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        return None
    lib.dpftpu_gen.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpftpu_gen.restype = ctypes.c_int
    lib.dpftpu_eval_expand.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpftpu_eval_expand.restype = ctypes.c_int
    lib.dpftpu_eval_point.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32)]
    lib.dpftpu_eval_point.restype = ctypes.c_int
    lib.dpftpu_eval_contract.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpftpu_eval_contract.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def gen(alpha: int, n: int, seed: bytes, prf_method: int):
    """Native keygen -> two [524] int32 numpy arrays (or None if no lib)."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    k0 = np.zeros(524, dtype=np.int32)
    k1 = np.zeros(524, dtype=np.int32)
    rc = lib.dpftpu_gen(
        alpha, n, seed, len(seed), prf_method,
        k0.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        k1.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise ValueError("native keygen failed (rc=%d)" % rc)
    return k0, k1


def eval_expand(key, prf_method: int):
    """Native full expansion -> [n] int32 (natural order), or None."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(np.asarray(key, dtype=np.int32).reshape(-1))
    # n lives in wire slot 130 (limbs 0 and 1): words 520/521 of 524
    n_lo, n_hi = 130 * 4, 130 * 4 + 1
    n = int(arr.view(np.uint32)[n_lo]) | (int(arr.view(np.uint32)[n_hi]) << 32)
    out = np.zeros(n, dtype=np.int32)
    rc = lib.dpftpu_eval_expand(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), prf_method,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise ValueError("native eval failed (rc=%d)" % rc)
    return out


def eval_contract(keys, prf_method: int, table, n_threads: int = 1):
    """Native batched expand+contract (the CPU baseline): keys [B,524] int32,
    table [n, E] int32 -> [B, E] int32 shares."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    kb = np.ascontiguousarray(np.stack(
        [np.asarray(k, dtype=np.int32).reshape(-1) for k in keys]))
    if kb.shape[1] != 524:
        raise ValueError("DPF keys must be 524 int32 words, got %d"
                         % kb.shape[1])
    tbl = np.ascontiguousarray(np.asarray(table, dtype=np.int32))
    out = np.zeros((kb.shape[0], tbl.shape[1]), dtype=np.int32)
    rc = lib.dpftpu_eval_contract(
        kb.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), kb.shape[0],
        prf_method, tbl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        tbl.shape[1], n_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        raise ValueError("native eval_contract failed (rc=%d)" % rc)
    return out
