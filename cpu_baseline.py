#!/usr/bin/env python
"""CPU-baseline benchmark harness (role of the reference's
``paper/kernel/cpu/dpf_google/benchmark.cu`` + its thread-sweep script):
measures native multithreaded CPU DPF expansion + fused contraction so the
TPU speedup tables have an in-repo CPU column.

Usage:
  python cpu_baseline.py [n_entries] [entry_size] [batch] [reps] [threads]
  python cpu_baseline.py --sweep     # thread sweep 1..N like the reference

Prints one python-dict result line per config (the printed-dict protocol).
"""

import json
import sys
import time

import numpy as np


def run(n_entries=16384, entry_size=16, batch=64, reps=3, threads=1,
        prf=3):
    import dpf_tpu
    from dpf_tpu import native

    if not native.available():
        print(json.dumps({"error": "native library unavailable"}))
        return None
    d = dpf_tpu.DPF(prf=prf)
    keys = [d.gen(int(i * 997) % n_entries, n_entries)[0]
            for i in range(min(batch, 16))]
    keys = [keys[i % len(keys)] for i in range(batch)]
    table = np.random.randint(0, 2 ** 31, (n_entries, entry_size),
                              dtype=np.int64).astype(np.int32)

    native.eval_contract(keys[:2], prf, table, n_threads=threads)  # warm
    t0 = time.time()
    for _ in range(reps):
        native.eval_contract(keys, prf, table, n_threads=threads)
    elapsed = time.time() - t0
    result = {
        "backend": "cpu-native",
        "entries": n_entries,
        "entry_size": entry_size,
        "batch_size": batch,
        "threads": threads,
        "prf": d.prf_method_string,
        "reps": reps,
        "elapsed_s": round(elapsed, 4),
        "dpfs_per_sec": int(batch * reps / elapsed),
    }
    print(json.dumps(result))
    return result


def thread_sweep(n_entries=16384, max_threads=None):
    import os
    if max_threads is None:
        max_threads = os.cpu_count() or 8
    t = 1
    while t <= max_threads:
        run(n_entries=n_entries, threads=t)
        t *= 2


if __name__ == "__main__":
    if "--sweep" in sys.argv:
        thread_sweep()
    else:
        args = [int(a) for a in sys.argv[1:]]
        names = ["n_entries", "entry_size", "batch", "reps", "threads"]
        run(**dict(zip(names, args)))
