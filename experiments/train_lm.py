#!/usr/bin/env python
"""Standalone LM training driver (reference ``main.py:16-56`` +
``train_model.sh`` reproduce path, TPU-native).

One command trains the flax LSTM LM on WikiText-style data (real files
when present under ``--data``, the synthetic markov stream otherwise),
checkpoints via Orbax, resumes from the checkpoint on re-run, and feeds
the trained model into ``evaluate_with_pir`` against a batch-PIR plan —
the full accuracy-vs-PIR-budget loop of the reference's LM workload
(``language_model_dataset.py:148-200``).

    python experiments/train_lm.py --epochs 2 --save ckpt_lm
    python experiments/train_lm.py --save ckpt_lm          # resumes
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="WikiText LSTM LM trainer (flax/optax, TPU-native)")
    ap.add_argument("--data", type=str, default="data/wikitext-2",
                    help="corpus dir (train.txt/valid.txt); synthetic "
                         "fallback when absent")
    ap.add_argument("--emsize", type=int, default=32,
                    help="token embedding size")
    ap.add_argument("--nhid", type=int, default=64,
                    help="LSTM hidden units")
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=32, help="sequence length")
    ap.add_argument("--vocab-limit", type=int, default=None,
                    help="cap vocabulary to most-frequent V words")
    ap.add_argument("--seed", type=int, default=1111)
    ap.add_argument("--save", type=str, default="ckpt_lm",
                    help="orbax checkpoint dir (resumed when present)")
    ap.add_argument("--eval-pir", action="store_true",
                    help="also evaluate under a batch-PIR recovery plan")
    ap.add_argument("--queries-to-hot", type=int, default=2)
    ap.add_argument("--dry-run", action="store_true",
                    help="tiny dataset + 1 epoch to verify the pipeline")
    ap.add_argument("--platform", choices=("auto", "cpu"), default="auto",
                    help="cpu = hermetic CPU backend (defeats the ambient "
                         "TPU-relay plugin; use for smoke runs)")
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        from dpf_tpu.utils.hermetic import force_cpu_mesh
        force_cpu_mesh(1)

    from dpf_tpu.models import checkpoint, lm
    from dpf_tpu.models.datasets import make_lm_dataset
    from dpf_tpu.models.loaders import load_wikitext

    if args.dry_run:
        ds = make_lm_dataset(vocab_size=200, seq_len=args.bptt,
                             n_train=40, n_val=10, seed=args.seed)
        args.epochs = 1
    elif os.path.exists(os.path.join(args.data, "train.txt")):
        ds = load_wikitext(args.data, seq_len=args.bptt,
                           vocab_limit=args.vocab_limit)
    else:
        print("# %s not found; using the synthetic markov stream"
              % args.data)
        ds = make_lm_dataset(seq_len=args.bptt, seed=args.seed)

    def init_fn():
        import jax
        import jax.numpy as jnp
        model = lm.LSTMLanguageModel(vocab_size=ds.vocab_size,
                                     embed_dim=args.emsize,
                                     hidden=args.nhid)
        params = model.init(jax.random.PRNGKey(args.seed),
                            jnp.zeros((1, ds.seq_len), jnp.int32))
        return model, params

    def train_fn():
        return lm.train_lm(ds, epochs=args.epochs,
                           batch_size=args.batch_size, lr=args.lr,
                           seed=args.seed, embed_dim=args.emsize,
                           hidden=args.nhid)

    resumed = os.path.exists(args.save)
    model, params = checkpoint.train_or_restore(args.save, init_fn,
                                                train_fn)
    result = {"vocab_size": ds.vocab_size, "seq_len": ds.seq_len,
              "resumed_from_checkpoint": resumed,
              "checkpoint": os.path.abspath(args.save)}
    result.update(lm.evaluate_with_pir(model, params, ds))

    if args.eval_pir:
        from dpf_tpu.apps.batch_pir import BatchPIROptimize, PIRConfig
        opt = BatchPIROptimize(
            ds.access_patterns("train"), ds.access_patterns("val"),
            pir_config=PIRConfig(queries_to_hot=args.queries_to_hot))
        pir_eval = lm.evaluate_with_pir(model, params, ds,
                                        pir_optimize=opt)
        result["pir"] = {"queries_to_hot": args.queries_to_hot,
                         **pir_eval}
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
