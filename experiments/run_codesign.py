#!/usr/bin/env python
"""End-to-end codesign experiment: the paper pipeline as one artifact.

Reproduces the reference's experimental flow (SURVEY.md §2.2 #23-#28)
against this framework's TPU backend:

  1. build a workload (synthetic rec or lm) and train its model
  2. sweep batch-PIR configs over the workload's access patterns
  3. (optionally) evaluate downstream model accuracy per config
  4. measure (or load) DPF eval throughput on the current backend
  5. join into latency-vs-recovery/accuracy frontier points + figures

  python experiments/run_codesign.py --workload rec --out /tmp/codesign \
      [--quick] [--with-accuracy] [--perf-from sweep_logs/*.log]
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", choices=["rec", "ratings", "lm"],
                    default="rec")
    ap.add_argument("--out", default="codesign_out")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--with-accuracy", action="store_true")
    ap.add_argument("--perf-from", default=None,
                    help="glob of benchmark logs; measures live if absent")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    import dpf_tpu
    from dpf_tpu.apps import codesign, plots, sweep
    from dpf_tpu.models import datasets
    from dpf_tpu.utils import scrape
    from dpf_tpu.utils.bench import test_dpf_perf

    # ---- 1. workload ----------------------------------------------------
    if args.workload in ("rec", "ratings"):
        make = (datasets.make_rec_dataset if args.workload == "rec"
                else datasets.make_ratings_dataset)
        ds = make(n_items=300 if args.quick else 2000,
                  n_users=60 if args.quick else 400)
        from dpf_tpu.models import rec as model_mod
        model, params = model_mod.train_rec_model(
            ds, epochs=2 if args.quick else 4)

        def accuracy_eval(opt):
            return model_mod.evaluate_with_pir(model, params, ds, opt)
    else:
        ds = datasets.make_lm_dataset(
            vocab_size=200 if args.quick else 1000,
            n_train=80 if args.quick else 300,
            n_val=10 if args.quick else 60)
        from dpf_tpu.models import lm as model_mod
        model, params = model_mod.train_lm(ds, epochs=1 if args.quick else 3)

        def accuracy_eval(opt):
            return model_mod.evaluate_with_pir(model, params, ds, opt)

    train_p = ds.access_patterns("train")
    val_p = ds.access_patterns("val")

    # ---- 2./3. batch-PIR config sweep ----------------------------------
    grid = None
    if args.quick:
        grid = {"cache_size_fraction": [0.5, 1.0], "num_collocate": [0],
                "bin_fraction": [0.1, 0.3], "queries_to_hot": [1, 2],
                "queries_to_cold": [0]}
    sweep_results = sweep.run_sweep(
        train_p, val_p, out_dir=os.path.join(args.out, "sweep"), grid=grid,
        eval_limit=50 if args.quick else None,
        model_eval=accuracy_eval if args.with_accuracy else None)

    # ---- 4. kernel perf -------------------------------------------------
    if args.perf_from:
        perf = [d for _, d in scrape.scrape_dir(args.perf_from)]
    else:
        sizes = [1024, 4096] if args.quick else [16384, 65536, 262144]
        perf = [test_dpf_perf(N=n, batch=64 if args.quick else 512,
                              prf=dpf_tpu.PRF_SALSA20,
                              reps=2 if args.quick else 5, quiet=True)
                for n in sizes]
    with open(os.path.join(args.out, "perf.json"), "w") as f:
        json.dump(perf, f, indent=1)

    # ---- 5. join + figures ---------------------------------------------
    points = codesign.join_sweep_with_perf(sweep_results, perf)
    frontier = codesign.pareto_frontier(points)
    with open(os.path.join(args.out, "frontier.json"), "w") as f:
        json.dump({"points": points, "frontier": frontier}, f, indent=1,
                  default=float)
    try:
        plots.plot_recovery_vs_queries(
            sweep_results, os.path.join(args.out, "recovery.png"))
        plots.plot_latency_vs_recovery(
            points, os.path.join(args.out, "frontier.png"),
            frontier=frontier)
        plots.plot_throughput_table(
            perf, os.path.join(args.out, "throughput.png"))
    except RuntimeError:
        pass  # matplotlib unavailable

    best = frontier[-1] if frontier else None
    print(json.dumps({
        "workload": args.workload,
        "configs_swept": len(sweep_results),
        "frontier_points": len(frontier),
        "best_recovery": best and best["mean_recovered"],
        "best_latency_ms": best and best["latency_ms"],
        "out": args.out,
    }, default=float))


if __name__ == "__main__":
    main()
