#!/usr/bin/env python
"""One-shot TPU tuning sweep: measure every knob combination, report best.

NOTE: the canonical relay-safe sweep is the ``tuning`` stage of
``experiments/tpu_all.py`` (single claim, JSONL persistence, newer knobs
incl. ``dispatch_group``/``radix``/``kernel_impl=pallas``); this script
remains as the quick manual one-shot.

Run on real TPU hardware (takes tens of minutes — each combination
compiles its own program):

  python experiments/tpu_tuning.py [--out tpu_tuning.json] [--quick]

Measures dpfs/sec for the headline configs across
  aes_impl {gather, bitsliced} x round_unroll {False, True}
  x dot_impl {i32, mxu}  (dot only matters at the contraction)
and prints a result-dict line per point plus a final summary with the
winning EvalConfig per PRF.
"""

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tpu_tuning.json")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--entries", type=int, default=None)
    ap.add_argument("--risky", action="store_true",
                    help="also measure monolithic bitsliced-AES programs "
                         "(compile may take tens of minutes via the relay "
                         "and MUST NOT be hard-killed mid-compile — see "
                         "docs/STATUS.md)")
    args = ap.parse_args()

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    n = args.entries or (16384 if args.quick else 65536)
    batch = 128 if args.quick else 512
    reps = 3 if args.quick else 10

    results = []

    def measure(prf, **knobs):
        cfg = EvalConfig(prf_method=prf, batch_size=batch, **knobs)
        cfg.apply_globals()
        try:
            r = test_dpf_perf(N=n, batch=batch, prf=prf, reps=reps,
                              quiet=True, config=cfg)
        except Exception as e:  # record failures, keep sweeping
            r = {"error": str(e)[:200], "dpfs_per_sec": 0,
                 "prf": {1: "SALSA20", 2: "CHACHA20", 3: "AES128"}.get(
                     prf, str(prf))}
        r.update({"knobs": knobs, "prf_id": prf})
        results.append(r)
        print(json.dumps(r), flush=True)
        return r["dpfs_per_sec"]

    # Ordered safest-compile first so a relay wedge late in the run
    # cannot erase earlier results (every point prints immediately).
    # AES headline: dispatch mode (per-level programs) x S-box x unroll
    for aes_impl, unroll in itertools.product(
            ("bitsliced:bp", "bitsliced:tower", "gather"), (False, True)):
        measure(dpf_tpu.PRF_AES128, aes_impl=aes_impl, round_unroll=unroll,
                kernel_impl="dispatch")
    # ChaCha: xla scan (small graphs; round-1-proven compile) x unroll
    # x dot, dispatch mode, then the Pallas subtree kernel
    for unroll, dot in itertools.product((False, True), ("i32", "mxu")):
        measure(dpf_tpu.PRF_CHACHA20, kernel_impl="xla",
                round_unroll=unroll, dot_impl=dot)
    measure(dpf_tpu.PRF_CHACHA20, kernel_impl="dispatch")
    measure(dpf_tpu.PRF_CHACHA20, kernel_impl="pallas")
    # Salsa: unroll x dot
    for unroll, dot in itertools.product((False, True), ("i32", "mxu")):
        measure(dpf_tpu.PRF_SALSA20, round_unroll=unroll, dot_impl=dot)
    # AES monolithic (gather first — ~100 s compile in round 1; bitsliced
    # monolithic only with --risky)
    measure(dpf_tpu.PRF_AES128, aes_impl="gather", round_unroll=False)
    if args.risky:
        for aes_impl, unroll in itertools.product(
                ("bitsliced:bp", "bitsliced:tower"), (False, True)):
            measure(dpf_tpu.PRF_AES128, aes_impl=aes_impl,
                    round_unroll=unroll)

    best = {}
    for r in results:
        if "error" in r:
            continue
        key = r["prf"]
        if key not in best or r["dpfs_per_sec"] > best[key]["dpfs_per_sec"]:
            best[key] = r
    summary = {"entries": n, "batch": batch,
               "best": {k: {"dpfs_per_sec": v["dpfs_per_sec"],
                            "knobs": v["knobs"]} for k, v in best.items()}}
    with open(args.out, "w") as f:
        json.dump({"results": results, "summary": summary}, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
