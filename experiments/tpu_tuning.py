#!/usr/bin/env python
"""One-shot TPU tuning sweep: measure every knob combination, report best.

Run on real TPU hardware (takes tens of minutes — each combination
compiles its own program):

  python experiments/tpu_tuning.py [--out tpu_tuning.json] [--quick]

Measures dpfs/sec for the headline configs across
  aes_impl {gather, bitsliced} x round_unroll {False, True}
  x dot_impl {i32, mxu}  (dot only matters at the contraction)
and prints a result-dict line per point plus a final summary with the
winning EvalConfig per PRF.
"""

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tpu_tuning.json")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--entries", type=int, default=None)
    args = ap.parse_args()

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    n = args.entries or (16384 if args.quick else 65536)
    batch = 128 if args.quick else 512
    reps = 3 if args.quick else 10

    results = []

    def measure(prf, **knobs):
        cfg = EvalConfig(prf_method=prf, batch_size=batch, **knobs)
        cfg.apply_globals()
        try:
            r = test_dpf_perf(N=n, batch=batch, prf=prf, reps=reps,
                              quiet=True)
        except Exception as e:  # record failures, keep sweeping
            r = {"error": str(e)[:200], "dpfs_per_sec": 0}
        r.update({"knobs": knobs, "prf_id": prf})
        results.append(r)
        print(json.dumps(r))
        return r["dpfs_per_sec"]

    # AES: the headline; all knob combos
    for aes_impl, unroll, dot in itertools.product(
            ("gather", "bitsliced"), (False, True), ("i32", "mxu")):
        measure(dpf_tpu.PRF_AES128, aes_impl=aes_impl, round_unroll=unroll,
                dot_impl=dot)
    # ChaCha/Salsa: unroll x dot
    for prf in (dpf_tpu.PRF_CHACHA20, dpf_tpu.PRF_SALSA20):
        for unroll, dot in itertools.product((False, True), ("i32", "mxu")):
            measure(prf, round_unroll=unroll, dot_impl=dot)

    best = {}
    for r in results:
        if "error" in r:
            continue
        key = r["prf"]
        if key not in best or r["dpfs_per_sec"] > best[key]["dpfs_per_sec"]:
            best[key] = r
    summary = {"entries": n, "batch": batch,
               "best": {k: {"dpfs_per_sec": v["dpfs_per_sec"],
                            "knobs": v["knobs"]} for k, v in best.items()}}
    with open(args.out, "w") as f:
        json.dump({"results": results, "summary": summary}, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
