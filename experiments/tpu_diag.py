#!/usr/bin/env python
"""Incremental TPU diagnostic: time compile vs run for each eval config.

The round-1/2 headline bench hit its watchdog while the relay answered
small programs quickly — this isolates whether the cost is XLA compile
time (graph size), device runtime, or the relay.  Prints one flushed
result line per stage so a wedge is attributable to a specific stage.

  python experiments/tpu_diag.py [--skip N]   # skip the first N stages
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stage(name, fn):
    t0 = time.time()
    try:
        out = fn()
        dt = time.time() - t0
        print(json.dumps({"stage": name, "ok": True,
                          "elapsed_s": round(dt, 2),
                          "extra": out if isinstance(out, dict) else None}),
              flush=True)
    except Exception as e:
        dt = time.time() - t0
        print(json.dumps({"stage": name, "ok": False,
                          "elapsed_s": round(dt, 2),
                          "error": "%s: %s" % (type(e).__name__,
                                               str(e)[:200])}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", type=int, default=0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    stage("devices", lambda: {"devices": str(jax.devices())})
    stage("tiny_matmul", lambda: float(
        (jnp.ones((256, 256)) @ jnp.ones((256, 256))).sum()))

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    def perf(prf, n, batch, reps, **knobs):
        cfg = EvalConfig(prf_method=prf, batch_size=batch, **knobs)
        cfg.apply_globals()
        r = test_dpf_perf(N=n, batch=batch, prf=prf, reps=reps, quiet=True,
                          keys_distinct=8, config=cfg)
        return {"dpfs_per_sec": r["dpfs_per_sec"],
                "elapsed_s": r["elapsed_s"]}

    stages = [
        # (name, thunk) — relay-safe ordering: dispatch mode (per-level
        # programs) before any monolithic graph; no monolithic bitsliced
        # AES at all (its compile can outlive any patience via the relay
        # and killing it mid-compile wedges the relay — docs/STATUS.md)
        ("dummy_n16k", lambda: perf(dpf_tpu.PRF_DUMMY, 16384, 64, 2)),
        ("chacha_n16k_disp", lambda: perf(dpf_tpu.PRF_CHACHA20, 16384, 64,
                                          2, kernel_impl="dispatch")),
        ("aes_bitsliced_n16k_disp", lambda: perf(
            dpf_tpu.PRF_AES128, 16384, 128, 2, aes_impl="bitsliced:bp",
            round_unroll=False, kernel_impl="dispatch")),
        ("aes_bitsliced_n64k_b512_disp", lambda: perf(
            dpf_tpu.PRF_AES128, 65536, 512, 3, aes_impl="bitsliced:bp",
            round_unroll=False, kernel_impl="dispatch")),
        ("chacha_n64k_b512_loop", lambda: perf(dpf_tpu.PRF_CHACHA20, 65536,
                                               512, 3, round_unroll=False)),
        ("chacha_n64k_b512_unroll", lambda: perf(dpf_tpu.PRF_CHACHA20,
                                                 65536, 512, 3,
                                                 round_unroll=True)),
        ("chacha_n64k_b512_pallas", lambda: perf(
            dpf_tpu.PRF_CHACHA20, 65536, 512, 3, kernel_impl="pallas")),
        ("aes_gather_n16k_loop", lambda: perf(dpf_tpu.PRF_AES128, 16384, 64,
                                              2, aes_impl="gather",
                                              round_unroll=False)),
    ]
    for i, (name, fn) in enumerate(stages):
        if i < args.skip:
            continue
        stage(name, fn)


if __name__ == "__main__":
    main()
