#!/usr/bin/env python
"""Consolidated single-process TPU measurement session.

Motivation (2026-07-29 incident, docs/STATUS.md): the axon relay grants
the chip to ONE process at a time, and the release after a clean process
exit is laggy (tens of seconds to minutes) — a process that tries to
claim during the lag can land in the relay's "grant unclaimed — client
lost" state and hang forever.  Running probe / tuning / sweeps / zoo as
separate processes therefore multiplies the hang risk by the number of
process transitions.  This script claims the device ONCE and runs every
measurement stage in that one process, appending each result line to
``--out`` (JSONL) the moment it exists, so a mid-session wedge can never
erase earlier stages.

  python experiments/tpu_all.py [--out tpu_results.jsonl] [--stages a,b,..]

Stages (safest/most-valuable first):
  probe      tiny matmul; prints PROBE_OK (watch the log for liveness)
  headline   AES128@65536 batch=512 dispatch — the bench.py metric
  tuning     knob sweep (aes_impl x unroll x dot x kernel_impl per PRF)
  table      README-style throughput table: N in {2^14..2^20} x 3 PRFs
  latency    warm batch=1 latency per PRF x N (coop-kernel role)
  large      2^22..2^26 single-chip large-table runs
  zoo        PRF-candidate throughput (paper's PRF-selection experiment)
  matmul     contraction-impl microbench (matmul_benchmark.cu role)
  profile    jax.profiler op-level traces for roofline verification
"""

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ALL_STAGES = ("probe", "headline", "tuning", "table", "latency", "large",
              "zoo", "matmul", "profile")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tpu_results.jsonl")
    ap.add_argument("--stages", default=",".join(ALL_STAGES))
    ap.add_argument("--deadline-s", type=int, default=4 * 3600,
                    help="soft overall deadline, checked between stages/"
                         "points (never interrupts a compile)")
    args = ap.parse_args()
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    # monotonic: this value feeds dispatch_deadline (the cooperative
    # per-level check compares against time.monotonic() since the NTP
    # fix) as well as the between-stages check below
    deadline = time.monotonic() + args.deadline_s
    out = open(args.out, "a", buffering=1)
    # one sid per session process: renderers scope to a single session so
    # retries / older rounds in the append-only file never mix
    sid = "%d.%d" % (os.getpid(), int(time.time()))

    n_ok = [0]  # non-error, non-skip measurement records this session

    def emit(stage, rec):
        rec = dict(rec)
        rec["stage"] = stage
        rec["sid"] = sid
        rec["t"] = round(time.time(), 1)
        # probe doesn't count: a session where only the tiny probe ran
        # but every measurement stage errored must NOT mark done:true
        # (the keepalive loop would stop retrying with zero data)
        if (stage not in ("session", "probe") and "error" not in rec
                and "skipped" not in rec):
            n_ok[0] += 1
        line = json.dumps(rec)
        out.write(line + "\n")
        print(line, flush=True)

    def guard(stage, fn, *a, **kw):
        """Run one measurement point; record errors, keep the session."""
        if time.monotonic() > deadline:
            emit(stage, {"skipped": "session deadline"})
            return None
        try:
            return fn(*a, **kw)
        except Exception as e:  # record + continue: partial data > none
            emit(stage, {"error": "%s: %s" % (type(e).__name__,
                                              str(e)[:300])})
            return None

    # Persistent compilation cache: a session retry after a mid-run
    # wedge (or a later round) reuses every executable already compiled
    # for identical (program, flags) keys instead of paying the relay
    # compile again.  Best-effort — harmless if the backend ignores it.
    try:
        import jax
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(repo, ".jax_compile_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception as e:
        print("compilation cache unavailable: %s" % e, flush=True)

    import dpf_tpu
    from dpf_tpu.utils.bench import (test_dpf_latency, test_dpf_perf,
                                     test_matmul_perf)
    from dpf_tpu.utils.config import EvalConfig

    def cfg_for(prf, batch, **kw):
        # AES always via dispatch mode (monolithic bitsliced compile can
        # outlive any watchdog through the relay; docs/STATUS.md)
        if prf == dpf_tpu.PRF_AES128 and "kernel_impl" not in kw:
            kw["kernel_impl"] = "dispatch"
            kw.setdefault("round_unroll", False)
        c = EvalConfig(prf_method=prf, batch_size=batch, **kw)
        c.apply_globals()
        return c

    def perf(stage, n, batch, prf, reps=5, check=True, **kw):
        # check=True everywhere by default: every recorded throughput row
        # passes the exact share-recovery gate before timing, so any row
        # is eligible as the headline (bench.py filters on ``checked``).
        # Cost is ~2 extra evals per point against a shared compile.
        cfg = cfg_for(prf, batch, **kw)
        r = test_dpf_perf(N=n, batch=batch, prf=prf, reps=reps,
                          quiet=True, check=check, config=cfg,
                          dispatch_deadline=deadline)
        r["knobs"] = kw
        emit(stage, r)
        return r

    # ---- probe ----
    if "probe" in stages:
        import jax
        import jax.numpy as jnp
        t0 = time.time()
        devs = jax.devices()
        x = jnp.ones((128, 128), jnp.int32)
        (x @ x).block_until_ready()
        print("PROBE_OK", flush=True)
        emit("probe", {"devices": [str(d) for d in devs],
                       "probe_s": round(time.time() - t0, 1)})

    # ---- headline (the bench.py metric, measured with check) ----
    if "headline" in stages:
        guard("headline", perf, "headline", 65536, 512,
              dpf_tpu.PRF_AES128, reps=10, check=True)

    # ---- tuning sweep ----
    if "tuning" in stages:
        aes_rows = []  # (result, kw) of every AES-headline-shaped point

        def tune(n, batch, prf, **kw):
            r = guard("tuning", perf, "tuning", n, batch, prf, reps=5, **kw)
            if (r and prf == dpf_tpu.PRF_AES128 and n == 65536
                    and batch == 512):
                aes_rows.append((r, kw))
            return r

        for aes_impl, unroll in itertools.product(
                ("bitsliced:bp", "bitsliced:tower", "gather"),
                (False, True)):
            tune(65536, 512, dpf_tpu.PRF_AES128,
                 aes_impl=aes_impl, round_unroll=unroll,
                 kernel_impl="dispatch")
        for unroll, dot in itertools.product((False, True), ("i32", "mxu")):
            tune(65536, 512, dpf_tpu.PRF_CHACHA20, kernel_impl="xla",
                 round_unroll=unroll, dot_impl=dot)
        tune(65536, 512, dpf_tpu.PRF_CHACHA20, kernel_impl="dispatch")
        tune(65536, 512, dpf_tpu.PRF_CHACHA20, kernel_impl="pallas")
        for unroll, dot in itertools.product((False, True), ("i32", "mxu")):
            tune(65536, 512, dpf_tpu.PRF_SALSA20,
                 round_unroll=unroll, dot_impl=dot)
        tune(65536, 512, dpf_tpu.PRF_SALSA20, kernel_impl="pallas")
        # dispatch-group A/B: fewer host round-trips (all subtrees in
        # one pass) vs the auto memory-bounded grouping
        tune(65536, 512, dpf_tpu.PRF_AES128, aes_impl="bitsliced:bp",
             round_unroll=False, kernel_impl="dispatch",
             dispatch_group=1 << 16)
        tune(65536, 512, dpf_tpu.PRF_AES128, aes_impl="bitsliced:bp",
             round_unroll=False, kernel_impl="dispatch",
             dispatch_group=1)
        # radix-4 construction (core/radix4.py): 2/3 the PRF children,
        # half the levels, 2x AES schedule amortization — vs binary above
        tune(65536, 512, dpf_tpu.PRF_AES128,
             radix=4, aes_impl="bitsliced:bp", round_unroll=False,
             kernel_impl="dispatch")
        tune(65536, 512, dpf_tpu.PRF_AES128,
             radix=4, aes_impl="bitsliced:bp", round_unroll=True,
             kernel_impl="dispatch")
        tune(65536, 512, dpf_tpu.PRF_CHACHA20, radix=4)
        tune(65536, 512, dpf_tpu.PRF_SALSA20, radix=4)
        # plane-domain Pallas AES level kernel (ops/aes_planes.py):
        # compiles as one small Mosaic program per level (relay-safe),
        # A/B vs the XLA bitsliced dispatch path above
        tune(65536, 512, dpf_tpu.PRF_AES128,
             kernel_impl="pallas", aes_impl="bitsliced:bp")
        tune(65536, 512, dpf_tpu.PRF_AES128,
             kernel_impl="pallas", aes_impl="bitsliced:bp", radix=4)
        # radix-4 ChaCha on the mixed-arity Pallas subtree kernel
        tune(65536, 512, dpf_tpu.PRF_CHACHA20, kernel_impl="pallas",
             radix=4)
        # block-PRG ("wide") stream ciphers: ONE 512-bit core block feeds
        # all children (core/prf_ref.py::prf_*_blk) — radix-4 blk costs
        # 1/4 the core calls of classic radix-4 and 1/6 of classic
        # binary; the expected ChaCha/Salsa throughput champions
        for prf_blk in (dpf_tpu.PRF_CHACHA20_BLK, dpf_tpu.PRF_SALSA20_BLK):
            tune(65536, 512, prf_blk, radix=4)
            tune(65536, 512, prf_blk, radix=4, kernel_impl="pallas")
            tune(65536, 512, prf_blk, kernel_impl="xla")
            tune(65536, 512, prf_blk, radix=4, kernel_impl="dispatch")
        # Re-measure the AES-headline winner at headline reps as a
        # "headline" row: bench.py prefers headline rows over raw sweep
        # rows, keeping the round-over-round metric definition fixed
        # ("best verified config, re-measured").
        if aes_rows:
            _, best_kw = max(aes_rows, key=lambda t: t[0]["dpfs_per_sec"])
            guard("headline", perf, "headline", 65536, 512,
                  dpf_tpu.PRF_AES128, reps=10, **best_kw)

    # ---- README-style throughput table ----
    if "table" in stages:
        for n in (1 << 14, 1 << 16, 1 << 18, 1 << 20):
            for prf in (dpf_tpu.PRF_AES128, dpf_tpu.PRF_SALSA20,
                        dpf_tpu.PRF_CHACHA20):
                guard("table", perf, "table", n, 512, prf, reps=5)
            # block-PRG rows (beyond the reference's table): radix-4 +
            # one core per node — the framework's fastest stream configs
            for prf in (dpf_tpu.PRF_SALSA20_BLK, dpf_tpu.PRF_CHACHA20_BLK):
                guard("table", perf, "table", n, 512, prf, reps=5,
                      radix=4)

    # ---- single-query latency ----
    if "latency" in stages:
        for n in (1 << 14, 1 << 16, 1 << 18, 1 << 20):
            for prf in (dpf_tpu.PRF_AES128, dpf_tpu.PRF_SALSA20,
                        dpf_tpu.PRF_CHACHA20):
                def lat(n=n, prf=prf):
                    cfg = cfg_for(prf, 1)
                    r = test_dpf_latency(N=n, prf=prf, quiet=True,
                                         config=cfg)
                    emit("latency", r)
                guard("latency", lat)
        # sqrt-N A/B: O(sqrt N) keys, flat single-level PRF grid — the
        # low-latency construction for mid-N (the reference serves this
        # regime with the coop kernel, dpf_gpu/dpf/dpf_coop.cu:3-9)
        for n in (1 << 14, 1 << 16, 1 << 17):
            for prf in (dpf_tpu.PRF_CHACHA20, dpf_tpu.PRF_AES128,
                        dpf_tpu.PRF_CHACHA20_BLK):
                def lat_sq(n=n, prf=prf):
                    cfg = cfg_for(prf, 1, scheme="sqrtn")
                    r = test_dpf_latency(N=n, prf=prf, quiet=True,
                                         config=cfg)
                    emit("latency", r)
                guard("latency", lat_sq)

    # ---- large tables ----
    if "large" in stages:
        for n in (1 << 22, 1 << 24, 1 << 26):
            for prf in (dpf_tpu.PRF_CHACHA20, dpf_tpu.PRF_AES128):
                guard("large", perf, "large", n, 64, prf, reps=3)
            guard("large", perf, "large", n, 64,
                  dpf_tpu.PRF_CHACHA20_BLK, reps=3, radix=4)

    # ---- PRF zoo ----
    if "zoo" in stages:
        def zoo():
            from dpf_tpu.core.prf_zoo import benchmark_zoo
            res = benchmark_zoo(n_calls=1 << 20, reps=5)
            # children/sec (= calls/sec x children-per-call) — the
            # metric the DPF cost model selects on
            emit("zoo", {"ggm_children_per_sec":
                         {k: int(v) for k, v in res.items()}})
        guard("zoo", zoo)

    # ---- contraction microbench ----
    if "matmul" in stages:
        def mm():
            for r in test_matmul_perf(quiet=True).values():
                emit("matmul", r)
        guard("matmul", mm)

    # ---- op-level traces for roofline verification ----
    if "profile" in stages:
        import numpy as np

        from dpf_tpu.utils.profiling import trace

        def prof(prf, name):
            from dpf_tpu.utils.profiling import summarize_trace
            n, batch = 65536, 512
            cfg = cfg_for(prf, batch)
            dpf = dpf_tpu.DPF(prf=prf, config=cfg)
            k1, _ = dpf.gen(7, n)
            dpf.eval_init(np.zeros((n, 16), dtype=np.int32))
            dpf.eval_tpu([k1] * batch)  # compile + warm outside the trace
            with trace(name, base_dir="tpu_traces") as path:
                dpf.eval_tpu([k1] * batch)
            rec = {"config": name, "trace_dir": path}
            try:  # a corrupt/truncated export must not lose trace_dir
                summary = summarize_trace(path)
            except Exception as e:
                summary = None
                rec["summary_error"] = "%s: %s" % (type(e).__name__,
                                                   str(e)[:120])
            if summary:  # op-level digest survives in the JSONL even if
                rec.update(summary)  # the raw trace directory is lost
            emit("profile", rec)
        guard("profile", prof, dpf_tpu.PRF_CHACHA20, "chacha_65536_b512")
        guard("profile", prof, dpf_tpu.PRF_AES128, "aes_dispatch_65536_b512")
        guard("profile", prof, dpf_tpu.PRF_CHACHA20_BLK,
              "chacha_blk_65536_b512")

    # "done" only if at least one stage produced real data; the keepalive
    # loop keys off this flag, and a session where every guarded stage
    # errored (e.g. relay UNAVAILABLE per-stage) must not stop it.
    emit("session", {"done": n_ok[0] > 0, "n_ok": n_ok[0]})


if __name__ == "__main__":
    main()
