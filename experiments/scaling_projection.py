#!/usr/bin/env python
"""North-star scaling projection: measured per-chip throughput -> the
2^32-entry multi-chip configuration (reference ``README.md:119`` claims
2^32-entry support on one GPU; BASELINE.json's north star is 2^32 entries
row-sharded over 64 chips).

  python experiments/scaling_projection.py [--results tpu_results.jsonl]
      [--chips 64] [--out docs/SCALING.md]

Model (see ``parallel/sharded.py``): the table is row-sharded, each chip
expands only its own GGM frontier subtrees against its local rows, and the
[B, E] int32 partial outputs are psum-reduced over ICI.

* Per-chip work at global size N over S chips == a single-chip run at
  N/S entries *plus* the replicated phase-1 frontier expansion
  (O(B*F), F <= a few thousand — noise next to O(B*N/S)).
* psum payload per batch: B x E x 4 B (512 x 16 x 4 = 32 KiB), vs
  v5e ICI ~45 GB/s/link -> well under a microsecond per hop; latency
  a few us per batch == negligible at batch times in the ms range.
* Key broadcast: B x 2 KiB = 1 MiB per batch over ICI, also negligible.

So projected dpfs/sec(N=2^32, S chips) ~= measured dpfs/sec(N=2^32/S,
one chip) with <1% collective overhead at batch >= 512.  The projection
below therefore quotes the measured single-chip number at N = 2^32/S as
the per-chip rate of the S-chip config; the batched-query throughput of
the whole mesh equals that same rate (every chip works on every query;
sharding divides the table, not the batch).
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default="tpu_results.jsonl")
    ap.add_argument("--chips", type=int, default=64)
    ap.add_argument("--sid", default=None,
                    help="project from this session id (default: the "
                         "latest completed session; 'all' merges every "
                         "session — manual use only)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from dpf_tpu.utils.results import (load_rows, round_start_t,
                                       session_rows)
    all_rows = load_rows(args.results)
    if args.sid == "all":
        scoped = all_rows
    elif args.sid is not None:
        scoped = session_rows(all_rows, args.sid)
    else:
        since = round_start_t()
        scoped = ([] if since is None
                  else session_rows(all_rows, since=since))
    rows = [r for r in scoped
            if r.get("dpfs_per_sec") and r.get("entries")
            and r.get("checked")]
    if not rows:
        print("no measured throughput rows in %s — run "
              "experiments/tpu_all.py first" % args.results)
        sys.exit(1)

    # best measured single-chip rate per (entries, prf)
    best = {}
    for r in rows:
        k = (r["entries"], r["prf"])
        if k not in best or r["dpfs_per_sec"] > best[k]["dpfs_per_sec"]:
            best[k] = r

    n_star = 1 << 32
    lines = [
        "# Scaling to the 2^32-entry north star",
        "",
        "Measured single-chip throughput at N entries == projected "
        "per-config throughput at global N x chips entries (table "
        "row-sharding, psum over ICI; overhead model in "
        "`experiments/scaling_projection.py`).",
        "",
        "| global N | chips | per-chip N | PRF | measured dpfs/sec "
        "(1 chip @ per-chip N) | projected dpfs/sec (mesh) |",
        "|---|---|---|---|---|---|",
    ]
    printed = False
    for chips in (1, 4, 16, args.chips):
        per_chip = n_star // chips
        for (entries, prf), r in sorted(best.items()):
            if entries == per_chip:
                lines.append(
                    "| 2^32 | %d | 2^%d | %s | %d | %d |"
                    % (chips, per_chip.bit_length() - 1, prf,
                       r["dpfs_per_sec"], r["dpfs_per_sec"]))
                printed = True
    if not printed:
        # no direct 2^32/S measurement: extrapolate 1/N from the largest
        biggest = max(best, key=lambda k: k[0])
        r = best[biggest]
        per_chip = n_star // args.chips
        scale = biggest[0] / per_chip
        lines.append(
            "| 2^32 | %d | 2^%d | %s | (extrapolated 1/N from N=2^%d: "
            "%d) | %d |"
            % (args.chips, per_chip.bit_length() - 1, biggest[1],
               biggest[0].bit_length() - 1, r["dpfs_per_sec"],
               int(r["dpfs_per_sec"] * scale)))
    lines += [
        "",
        "Collective overhead at batch 512: psum payload 32 KiB + key "
        "broadcast ~1 MiB per batch — <1% of a millisecond-scale batch "
        "on v5e ICI.",
    ]
    text = "\n".join(lines) + "\n"
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print("wrote %s" % args.out)


if __name__ == "__main__":
    main()
