#!/usr/bin/env python
"""Large-N functional run on the virtual 8-device CPU mesh.

VERDICT.md (round 2) item 4 asks for "an 8-way CPU-mesh functional run at
the largest N memory allows" to back the large-N story with an executed
multi-device data point (the reference exercises 2^22..2^26 single-GPU in
``paper/kernel/gpu/scripts/sweep.sh:3-14`` and claims 2^32 support,
``README.md:119``; the TPU build's 2^32 path is the row-sharded mesh in
``parallel/sharded.py``).

This script actually *runs* the mesh-sharded evaluation at table sizes
limited only by host memory and single-core patience, verifying recovery
(server A share - server B share == table row) at every size.  Throughput
numbers from a 1-core CPU host are meaningless and are recorded only as
wall-clock provenance, never as perf claims.

  python experiments/cpu_mesh_large.py [--max-log-n 24] [--batch 4]
      [--out cpu_mesh_results.jsonl]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dpf_tpu.utils.hermetic import force_cpu_mesh  # noqa: E402

force_cpu_mesh(8)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-log-n", type=int, default=20)
    ap.add_argument("--max-log-n", type=int, default=24)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--entry-size", type=int, default=16)
    ap.add_argument("--deadline-s", type=int, default=3600)
    from dpf_tpu.core.prf_ref import PRF_NAMES
    ap.add_argument("--prf", default="CHACHA20",
                    choices=sorted(PRF_NAMES.values()),
                    help="PRF name, e.g. CHACHA20 or CHACHA20_BLK")
    ap.add_argument("--radix", type=int, default=2, choices=(2, 4))
    ap.add_argument("--out", default="cpu_mesh_results.jsonl")
    args = ap.parse_args()
    deadline = time.time() + args.deadline_s

    import numpy as np

    from dpf_tpu import DPF
    from dpf_tpu.parallel import sharded
    from dpf_tpu.utils.config import EvalConfig

    prf_id = {v: k for k, v in PRF_NAMES.items()}[args.prf]
    out = open(args.out, "a", buffering=1)

    def emit(rec):
        rec["t"] = round(time.time(), 1)
        line = json.dumps(rec)
        out.write(line + "\n")
        print(line, flush=True)

    mesh = sharded.make_mesh(n_table=8, n_batch=1)
    dpf = DPF(config=EvalConfig(prf_method=prf_id, radix=args.radix))
    rng = np.random.default_rng(0)

    for log_n in range(args.min_log_n, args.max_log_n + 1):
        if time.time() > deadline:
            emit({"stage": "cpu_mesh_large", "log_n": log_n,
                  "skipped": "deadline"})
            break
        n = 1 << log_n
        # Spot-verify at a handful of rows instead of materializing the
        # whole random table twice: table rows are a deterministic hash of
        # the row index, so table[idx] is recomputable without keeping a
        # second copy.
        t_build = time.time()
        # all-uint32 build: wraparound IS the mod-2^32, so peak memory is
        # the table plus one same-size broadcast temp (an int64
        # intermediate would be a 2x transient — the same trap
        # utils/bench.py:44-46 documents for the large-N sweep)
        table = (np.arange(n, dtype=np.uint32)[:, None]
                 * np.uint32(2654435761)
                 + np.arange(args.entry_size, dtype=np.uint32)[None, :]
                 * np.uint32(40503)).view(np.int32)
        srv = sharded.ShardedDPFServer(
            table, mesh, prf_method=prf_id, batch_size=args.batch,
            radix=args.radix)
        t_build = time.time() - t_build

        idxs = [int(rng.integers(0, n)) for _ in range(args.batch)]
        keys = [dpf.gen(i, n) for i in idxs]
        t0 = time.time()
        a = srv.eval([k[0] for k in keys])
        b = srv.eval([k[1] for k in keys])
        wall = time.time() - t0
        rec = (a - b).astype(np.int32)
        ok = bool((rec == table[idxs]).all())
        emit({"stage": "cpu_mesh_large", "log_n": log_n, "n": n,
              "batch": args.batch, "entry_size": args.entry_size,
              "mesh": dict(mesh.shape), "prf": args.prf,
              "radix": args.radix,
              "recovered_ok": ok, "build_s": round(t_build, 1),
              "eval2_wall_s": round(wall, 1),
              "table_mib": round(table.nbytes / 2 ** 20, 1)})
        if not ok:
            sys.exit(1)
        del table, srv

    emit({"stage": "cpu_mesh_large", "done": True})


if __name__ == "__main__":
    main()
