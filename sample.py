# sample.py
# ------------------------------------
# Example usage of the TPU-DPF interface (mirrors the reference's
# sample.py walkthrough, reference sample.py:1-59, but runs on TPU).
#
# Problem setting:
# - A client wants one entry from a table replicated on two
#   non-colluding servers, without revealing which entry.
#
# Solution:
# - Client builds a DPF for its secret index and sends one ~2 KB key
#   to each server.
# - Each server expands its key on TPU against the whole table and
#   returns a single additive share (16 int32 words).
# - The client subtracts the shares to recover the entry.

import numpy as np

import dpf_tpu

# Table parameters
table_size = 16384
entry_size = 1

# The actual table (replicated on 2 non-colluding servers)
table = np.random.randint(0, 2 ** 31, (table_size, entry_size)).astype(np.int32)
table[42, :] = 42


def server(k):
    # Server initializes DPF with the table and evaluates the key on TPU
    dpf_ = dpf_tpu.DPF(prf=dpf_tpu.PRF_SALSA20)
    dpf_.eval_init(table)
    return np.asarray(dpf_.eval_tpu([k]))


def client():
    secret_indx = 42

    # Generate two keys that represent the secret index
    dpf_ = dpf_tpu.DPF(prf=dpf_tpu.PRF_SALSA20)
    k1, k2 = dpf_.gen(secret_indx, table_size)

    # Send one key to each server to evaluate.
    # Assuming the two servers do not collude, neither learns
    # anything about secret_indx.
    a = int(server(k1)[0, 0])
    b = int(server(k2)[0, 0])

    rec = int(np.int32(np.uint32(a) - np.uint32(b)))

    print(a, b, rec)
    assert rec == 42
    print("Recovered table[42] privately.")


if __name__ == "__main__":
    client()
