#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: server-side batched DPF evaluation throughput (dpfs/sec) at
entries=65536, entry_size=16, PRF=AES-128, batch=512 on one TPU chip —
the reference's V100 number for this config is 15,392 dpfs/sec
(README.md:130); vs_baseline = ours / V100.

Relay-safety design (docs/STATUS.md incidents):

* The axon relay grants the chip to ONE process at a time and releases
  a clean exit's grant lazily; a second process claiming during the lag
  can hang forever ("client lost").  So probe and measurement run in a
  SINGLE detached worker process (one claim total): the worker prints
  ``PROBE_OK`` right after its first tiny device op, then measures.
  The parent watches the worker's log — no PROBE_OK within PROBE_S
  means the relay is wedged (diagnosed cheaply); a result line means
  success.
* Killing a process mid-compile wedges the relay for every later
  process.  On timeout the parent *abandons* the worker
  (``start_new_session``; never killed) and the worker itself aborts
  only cooperatively *between* dispatches (``expand.DeadlineExceeded``).
* ``kernel_impl="dispatch"`` (one small XLA program per GGM level,
  seconds each to compile) — never one monolithic program whose
  compile could outlive any watchdog.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

BASELINE_V100_AES128_65536 = 15392.0
PROBE_S = int(os.environ.get("DPF_BENCH_PROBE_S", "300"))
SOFT_DEADLINE_S = int(os.environ.get("DPF_BENCH_SOFT_S", "1800"))
WATCHDOG_S = int(os.environ.get("DPF_BENCH_WATCHDOG_S", "2700"))


def _result(value, n, extra=None):
    r = {
        "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, batch=512, "
                  "1 chip)" % n,
        "value": value,
        "unit": "dpfs/sec",
        "vs_baseline": round(value / BASELINE_V100_AES128_65536, 4),
    }
    if extra:
        r.update(extra)
    print(json.dumps(r), flush=True)
    return r


def _worker_main(n):
    """Probe + measurement, one process, one relay claim."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    # Probe: first device contact with a tiny program.  PROBE_OK in the
    # log tells the parent the relay granted us the chip.
    x = jnp.ones((128, 128), jnp.float32)
    (x @ x).block_until_ready()
    print("PROBE_OK", flush=True)

    batch = 512
    cfg = EvalConfig(prf_method=dpf_tpu.PRF_AES128, batch_size=batch,
                     kernel_impl="dispatch", round_unroll=False)
    cfg.apply_globals()

    # Warm phase THROUGH THE API (same code path and jit caches the
    # measured run hits) with the cooperative deadline armed: every
    # per-level program compiles here, abortable between dispatches.
    deadline = time.time() + SOFT_DEADLINE_S
    dpf = dpf_tpu.DPF(prf=dpf_tpu.PRF_AES128, config=cfg)
    k1, _ = dpf.gen(7, n)
    dpf.eval_init(np.zeros((n, 16), dtype=np.int32))
    dpf.dispatch_deadline = deadline
    dpf.eval_tpu([k1] * batch)

    # Measured run via the shared harness: 512 distinct keys + exact
    # share-recovery gate (check=True) + timed reps, under the same
    # cooperative deadline.
    r = test_dpf_perf(N=n, batch=batch, entrysize=16,
                      prf=dpf_tpu.PRF_AES128, reps=10, quiet=True,
                      check=True, config=cfg, dispatch_deadline=deadline)
    _result(r["dpfs_per_sec"], n,
            {"config": "dispatch/bitsliced-bp/loop-rounds",
             "elapsed_s": r["elapsed_s"]})


def main():
    pos = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(pos[0]) if pos else 65536

    if "--run-worker" in sys.argv:
        _worker_main(n)
        return

    fd, log = tempfile.mkstemp(prefix="dpf_bench_", suffix=".log")
    worker = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), str(n), "--run-worker"],
        stdout=fd, stderr=fd, start_new_session=True)
    os.close(fd)

    def read_log():
        with open(log) as f:
            return f.read()

    # Phase 1: wait for first device contact (PROBE_OK in the log).
    t0 = time.time()
    probed = False
    while time.time() - t0 < PROBE_S:
        if worker.poll() is not None or "PROBE_OK" in read_log():
            probed = "PROBE_OK" in read_log()
            break
        time.sleep(2)
    if not probed:  # final re-read: PROBE_OK may land during the last sleep
        probed = "PROBE_OK" in read_log()
    if not probed and worker.poll() is None:
        _result(0, n, {"error": "TPU relay unresponsive to the worker's "
                                "tiny probe program after %ds (wedged); "
                                "worker abandoned, not killed" % PROBE_S})
        sys.exit(2)

    # Phase 2: wait for the result line.
    rc = None
    try:
        rc = worker.wait(WATCHDOG_S)
    except subprocess.TimeoutExpired:
        pass  # abandoned, still running
    out = read_log().strip()
    line = next((ln for ln in reversed(out.splitlines())
                 if ln.startswith("{")), None)
    if line and rc in (0, None):
        # rc None with a result line: the measurement completed and the
        # worker hung in teardown (grant release) — keep the number
        print(line, flush=True)
        return
    if rc is None:
        _result(0, n, {"error": "TPU backend unresponsive after %ds "
                                "(relay wedged mid-run?); worker "
                                "abandoned, not killed" % WATCHDOG_S})
        sys.exit(2)
    _result(0, n, {"error": "worker exited rc=%s; tail: %s"
                            % (rc, out[-300:])})
    sys.exit(3)


if __name__ == "__main__":
    main()
