#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: server-side batched DPF evaluation throughput (dpfs/sec) at
entries=65536, entry_size=16, PRF=AES-128, batch=512 on one TPU chip —
the reference's V100 number for this config is 15,392 dpfs/sec
(README.md:130); vs_baseline = ours / V100.  The value is the BEST
correctness-gated configuration of this workload measured this round
(the reference's table likewise quotes its tuned hybrid kernel): the
single-claim session's tuning sweep re-measures its winner as a
"headline" row, which outranks raw sweep rows here; the --live worker
measures the fixed conservative config (dispatch/bitsliced-bp, binary)
when no session row exists.

Relay-safety design (docs/STATUS.md incidents):

* The axon relay grants the chip to ONE process at a time and releases
  a clean exit's grant lazily; a second process claiming during the lag
  can hang forever ("client lost").  So probe and measurement run in a
  SINGLE detached worker process (one claim total): the worker prints
  ``PROBE_OK`` right after its first tiny device op, then measures.
  The parent watches the worker's log — no PROBE_OK within PROBE_S
  means the relay is wedged (diagnosed cheaply); a result line means
  success.
* Killing a process mid-compile wedges the relay for every later
  process.  On timeout the parent *abandons* the worker
  (``start_new_session``; never killed) and the worker itself aborts
  only cooperatively *between* dispatches (``expand.DeadlineExceeded``).
* ``kernel_impl="dispatch"`` (one small XLA program per GGM level,
  seconds each to compile) — never one monolithic program whose
  compile could outlive any watchdog.
* Round-3 lesson: the driver runs this script at round end while the
  measurement keepalive (``scripts/tpu_keepalive.sh`` ->
  ``experiments/tpu_all.py``) may still hold or be queued on the relay —
  spawning a second claimant then is exactly the grant-contention wedge.
  So: if the single-claim session already measured the headline this
  round, report that row (with provenance) without touching the relay;
  if another claimant process is alive, refuse to add one; only
  otherwise claim live.  ``--live`` forces a live claim.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

BASELINE_V100_AES128_65536 = 15392.0
PROBE_S = int(os.environ.get("DPF_BENCH_PROBE_S", "300"))
SOFT_DEADLINE_S = int(os.environ.get("DPF_BENCH_SOFT_S", "1800"))
WATCHDOG_S = int(os.environ.get("DPF_BENCH_WATCHDOG_S", "2700"))


def _result(value, n, extra=None):
    r = {
        "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, batch=512, "
                  "1 chip)" % n,
        "value": value,
        "unit": "dpfs/sec",
        "vs_baseline": round(value / BASELINE_V100_AES128_65536, 4),
    }
    if extra:
        r.update(extra)
    print(json.dumps(r), flush=True)
    return r


def _worker_main(n):
    """Probe + measurement, one process, one relay claim."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    # Probe: first device contact with a tiny program.  PROBE_OK in the
    # log tells the parent the relay granted us the chip.
    x = jnp.ones((128, 128), jnp.float32)
    (x @ x).block_until_ready()
    print("PROBE_OK", flush=True)

    batch = 512
    cfg = EvalConfig(prf_method=dpf_tpu.PRF_AES128, batch_size=batch,
                     kernel_impl="dispatch", round_unroll=False)
    cfg.apply_globals()

    # Warm phase THROUGH THE API (same code path and jit caches the
    # measured run hits) with the cooperative deadline armed: every
    # per-level program compiles here, abortable between dispatches.
    # (monotonic — the dispatch deadline contract since the NTP fix)
    deadline = time.monotonic() + SOFT_DEADLINE_S
    dpf = dpf_tpu.DPF(prf=dpf_tpu.PRF_AES128, config=cfg)
    k1, _ = dpf.gen(7, n)
    dpf.eval_init(np.zeros((n, 16), dtype=np.int32))
    dpf.dispatch_deadline = deadline
    dpf.eval_tpu([k1] * batch)

    # Measured run via the shared harness: 512 distinct keys + exact
    # share-recovery gate (check=True) + timed reps, under the same
    # cooperative deadline.
    r = test_dpf_perf(N=n, batch=batch, entrysize=16,
                      prf=dpf_tpu.PRF_AES128, reps=10, quiet=True,
                      check=True, config=cfg, dispatch_deadline=deadline)
    _result(r["dpfs_per_sec"], n,
            {"config": "dispatch/bitsliced-bp/loop-rounds",
             "elapsed_s": r["elapsed_s"]})


def _cached_headline(n, path=None, since=None):
    """Best correctness-gated headline-config row measured this round by
    the single-claim session (``experiments/tpu_all.py --out
    tpu_results.jsonl``), or None.  Rows must carry ``checked: true``
    (exact share-recovery gate ran before timing) and a timestamp at or
    after ``since`` (defaults to the current round's start, FAIL CLOSED
    when unknowable).  The latest session COMPLETED this round is
    preferred (the scope the renderers publish); rows from this round's
    incomplete sessions are the fallback — a wedge after the headline
    stage must not discard a real gated measurement."""
    repo = os.path.dirname(os.path.abspath(__file__))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from dpf_tpu.utils.results import (latest_done_sid, load_rows,
                                           round_start_t, session_rows)
    except ImportError:
        return None  # library not importable -> no cache, measure live
    if path is None:
        path = os.path.join(repo, "tpu_results.jsonl")
    if since is None:
        since = round_start_t(repo)
        if since is None:
            return None
    rows = load_rows(path)
    sid = latest_done_sid(rows, since=since)
    sess = session_rows(rows, sid=sid, since=since) if sid else []

    def this_round(r):
        try:
            return float(r.get("t", 0)) >= since
        except (TypeError, ValueError):
            return False

    def pick(cands):
        best = None
        for r in cands:
            try:
                if (r.get("stage") in ("headline", "table", "tuning")
                        and r.get("entries") == n
                        and r.get("prf") == "AES128"
                        and r.get("batch_size") == 512
                        and r.get("checked")
                        and float(r.get("dpfs_per_sec") or 0) > 0):
                    # "headline" rows outrank tuning/table rows at any
                    # speed: the headline stage re-measures the tuning
                    # winner, so the metric definition ("best verified
                    # config, re-measured at headline reps") stays
                    # comparable round over round
                    key = (r["stage"] == "headline",
                           float(r["dpfs_per_sec"]))
                    if best is None or key > (best["stage"] == "headline",
                                              float(best["dpfs_per_sec"])):
                        best = r
            except (ValueError, TypeError, AttributeError):
                continue  # wrongly-typed field
        return best

    # Fallback order when the published scope (latest completed session)
    # holds no ELIGIBLE row — not merely no rows at all:
    #   1. this round's wedged/INCOMPLETE sessions (a wedge after the
    #      headline stage must not discard a real gated measurement);
    #   2. last resort, OTHER completed sessions of the round.
    # Preferring (1) keeps bench aligned with report.py (which renders
    # only the latest completed session) whenever possible, but a
    # checked row anywhere in the round always beats reporting 0
    # (round-4 verdict: never end a round at 0 with real data on disk).
    done_sids = {r.get("sid") for r in rows
                 if r.get("stage") == "session" and r.get("done")
                 and this_round(r)}
    incomplete = [r for r in rows if this_round(r)
                  and r.get("sid") not in done_sids]
    any_round = [r for r in rows if this_round(r)]
    return pick(sess) or pick(incomplete) or pick(any_round)


def _relay_health():
    """One-line health-probe timeline from the keepalive log (or None)
    — attached to failure reports so the driver-recorded BENCH json
    itself proves whether the relay was down (round-4 verdict: a
    relay-down round must show the probe timeline)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    log = os.path.join(repo, "tpu_keepalive.log")
    try:
        scripts = os.path.join(repo, "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        from relay_timeline import summarize
        line = summarize(log)
        # summarize's own can't-read/no-attempts strings are not
        # evidence — report nothing rather than noise
        if line.startswith("relay timeline (%s): " % log):
            return line
        return None
    except Exception:
        return None


def _fail(value_n, msg, exit_code=2):
    """Print a failure result (with the relay-health timeline attached
    when available) and exit."""
    extra = {"error": msg}
    health = _relay_health()
    if health:
        extra["relay_health"] = health
    _result(0, value_n, extra)
    sys.exit(exit_code)


def _other_claimant():
    """PID + cmdline of a live TPU claimant process (the keepalive
    session or another bench worker), or None.  Never add a second
    claimant next to one (docs/STATUS.md).  Scans /proc directly so the
    guard cannot fail open when pgrep is absent."""
    me = os.getpid()
    try:
        pids = [d for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        pids = []
    for pid in pids:
        if int(pid) == me:
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                argv = [a.decode("utf-8", "replace")
                        for a in f.read().split(b"\0") if a]
        except OSError:
            continue  # raced exit
        # Exact argv-token matching, AND argv[0] must be an interpreter:
        # a shell -c blob that merely MENTIONS these script names is one
        # long token (no match), and an editor/tail/grep holding the
        # script path has a non-interpreter argv[0] (no match).  A real
        # claimant is python running the script / sh running the loop.
        if not argv:
            continue
        a0 = os.path.basename(argv[0])
        names = {os.path.basename(a) for a in argv}
        is_py = a0.startswith("python")
        is_sh = a0 in ("sh", "bash", "dash", "ash")
        if ((is_py and "tpu_all.py" in names)
                or (is_sh and "tpu_keepalive.sh" in names)
                or (is_py and "--run-worker" in argv
                    and "bench.py" in names)):
            return "%s %s" % (pid, " ".join(argv))
    return None


def _claim_lock():
    """Take the shared claimant mutex (the same file the keepalive loop
    flocks) non-blocking.  Returns the open fd on success (KEEP IT OPEN
    and pass it to the worker: the lock lives exactly as long as some
    process holds the fd), or None when another claimant holds it.
    The one-shot /proc scan alone is check-then-spawn racy; this lock is
    the principal mutual exclusion, the scan the fallback for claimants
    that never took it."""
    lock_path = os.environ.get("LOCK_FILE", "/tmp/tpu_keepalive.lock")
    try:
        import fcntl
    except ImportError:
        return -1  # no fcntl: fall back to the scan only
    fd = os.open(lock_path, os.O_WRONLY | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        return None
    return fd


def main():
    pos = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(pos[0]) if pos else 65536

    if "--run-worker" in sys.argv:
        _worker_main(n)
        return

    if "--live" not in sys.argv:
        cached = _cached_headline(n)
        if cached:
            _result(float(cached["dpfs_per_sec"]), n, {
                "source": "tpu_results.jsonl (single-claim TPU session, "
                          "experiments/tpu_all.py)",
                "measured_unix_t": cached.get("t"),
                "stage": cached.get("stage"),
                "config": cached.get("knobs"),
                "elapsed_s": cached.get("elapsed_s"),
            })
            return
        claimant = _other_claimant()
        if claimant:
            _fail(n, "another TPU claimant is alive (%s); refusing a "
                     "second concurrent claim (grant-contention "
                     "discipline, docs/STATUS.md) and no measured "
                     "headline is on disk yet" % claimant)

    # Principal mutual exclusion vs the keepalive loop (which flocks the
    # same file for its whole lifetime): no lock, no claim.  The worker
    # inherits the fd so the lock is held exactly as long as the
    # (possibly abandoned) claimant lives.
    lock_fd = _claim_lock()
    if lock_fd is None:
        _result(0, n, {"error": "claimant mutex /tmp/tpu_keepalive.lock "
                                "is held (keepalive session or another "
                                "bench worker); refusing a second "
                                "concurrent claim"})
        sys.exit(2)

    fd, log = tempfile.mkstemp(prefix="dpf_bench_", suffix=".log")
    worker = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), str(n), "--run-worker"],
        stdout=fd, stderr=fd, start_new_session=True,
        pass_fds=(lock_fd,) if lock_fd >= 0 else ())
    os.close(fd)
    if lock_fd >= 0:
        os.close(lock_fd)  # the worker's inherited copy keeps it held

    def read_log():
        with open(log) as f:
            return f.read()

    # Phase 1: wait for first device contact (PROBE_OK in the log).
    t0 = time.time()
    probed = False
    while time.time() - t0 < PROBE_S:
        if worker.poll() is not None or "PROBE_OK" in read_log():
            probed = "PROBE_OK" in read_log()
            break
        time.sleep(2)
    if not probed:  # final re-read: PROBE_OK may land during the last sleep
        probed = "PROBE_OK" in read_log()
    if not probed and worker.poll() is None:
        _fail(n, "TPU relay unresponsive to the worker's tiny probe "
                 "program after %ds (wedged); worker abandoned, not "
                 "killed" % PROBE_S)

    # Phase 2: wait for the result line.
    rc = None
    try:
        rc = worker.wait(WATCHDOG_S)
    except subprocess.TimeoutExpired:
        pass  # abandoned, still running
    out = read_log().strip()
    line = next((ln for ln in reversed(out.splitlines())
                 if ln.startswith("{")), None)
    if line and rc in (0, None):
        # rc None with a result line: the measurement completed and the
        # worker hung in teardown (grant release) — keep the number
        print(line, flush=True)
        return
    if rc is None:
        _fail(n, "TPU backend unresponsive after %ds (relay wedged "
                 "mid-run?); worker abandoned, not killed" % WATCHDOG_S)
    _fail(n, "worker exited rc=%s; tail: %s" % (rc, out[-300:]),
          exit_code=3)


if __name__ == "__main__":
    main()
