#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: server-side batched DPF evaluation throughput (dpfs/sec) at
entries=65536, entry_size=16, PRF=AES-128, batch=512 on one TPU chip —
the reference's V100 number for this config is 15,392 dpfs/sec
(README.md:130); vs_baseline = ours / V100.
"""

import json
import sys

BASELINE_V100_AES128_65536 = 15392.0


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf

    r = test_dpf_perf(N=n, batch=512, entrysize=16,
                      prf=dpf_tpu.PRF_AES128, reps=10, quiet=True)
    print(json.dumps({
        "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, batch=512, "
                  "1 chip)" % n,
        "value": r["dpfs_per_sec"],
        "unit": "dpfs/sec",
        "vs_baseline": round(r["dpfs_per_sec"] / BASELINE_V100_AES128_65536,
                             4),
    }))


if __name__ == "__main__":
    main()
