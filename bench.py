#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: server-side batched DPF evaluation throughput (dpfs/sec) at
entries=65536, entry_size=16, PRF=AES-128, batch=512 on one TPU chip —
the reference's V100 number for this config is 15,392 dpfs/sec
(README.md:130); vs_baseline = ours / V100.

Relay-safety design (docs/STATUS.md incident): killing a process while it
is inside a TPU-relay compile wedges the relay for every later process.
So this bench:

* probes the backend with a tiny program first, and evaluates via
  ``kernel_impl="dispatch"`` — one small XLA program per GGM level,
  seconds each to compile — never one monolithic program whose compile
  could outlive any watchdog;
* runs both the probe and the measurement as **detached subprocesses**
  (``start_new_session``) and, on timeout, *abandons* them (reports and
  exits, leaving the child to finish or wait harmlessly) instead of
  killing them mid-compile;
* aborts on its soft deadline cooperatively *between* dispatches
  (``expand.DeadlineExceeded``).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

BASELINE_V100_AES128_65536 = 15392.0
PROBE_S = int(os.environ.get("DPF_BENCH_PROBE_S", "300"))
SOFT_DEADLINE_S = int(os.environ.get("DPF_BENCH_SOFT_S", "1800"))
WATCHDOG_S = int(os.environ.get("DPF_BENCH_WATCHDOG_S", "2700"))


def _result(value, n, extra=None):
    r = {
        "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, batch=512, "
                  "1 chip)" % n,
        "value": value,
        "unit": "dpfs/sec",
        "vs_baseline": round(value / BASELINE_V100_AES128_65536, 4),
    }
    if extra:
        r.update(extra)
    print(json.dumps(r), flush=True)


def _wait_abandon(proc, timeout_s):
    """Wait for a detached child; on timeout leave it running (never kill
    a process that may hold the TPU grant mid-compile)."""
    try:
        return proc.wait(timeout_s)
    except subprocess.TimeoutExpired:
        return None  # abandoned, still running


def _probe_main():
    import jax
    import jax.numpy as jnp
    jax.devices()
    x = jnp.ones((128, 128), jnp.float32)
    (x @ x).block_until_ready()
    print("PROBE_OK", flush=True)


def _run_main(n):
    import numpy as np

    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf
    from dpf_tpu.utils.config import EvalConfig

    batch = 512
    cfg = EvalConfig(prf_method=dpf_tpu.PRF_AES128, batch_size=batch,
                     kernel_impl="dispatch", round_unroll=False)
    cfg.apply_globals()

    # Warm phase THROUGH THE API (same code path and jit caches the
    # measured run hits) with the cooperative deadline armed: every
    # per-level program compiles here, abortable between dispatches.
    deadline = time.time() + SOFT_DEADLINE_S
    dpf = dpf_tpu.DPF(prf=dpf_tpu.PRF_AES128, config=cfg)
    k1, _ = dpf.gen(7, n)
    dpf.eval_init(np.zeros((n, 16), dtype=np.int32))
    dpf.dispatch_deadline = deadline
    dpf.eval_tpu([k1] * batch)

    # Measured run via the shared harness: 512 distinct keys + exact
    # share-recovery gate (check=True) + timed reps, under the same
    # cooperative deadline.
    r = test_dpf_perf(N=n, batch=batch, entrysize=16,
                      prf=dpf_tpu.PRF_AES128, reps=10, quiet=True,
                      check=True, config=cfg, dispatch_deadline=deadline)
    _result(r["dpfs_per_sec"], n,
            {"config": "dispatch/bitsliced-bp/loop-rounds",
             "elapsed_s": r["elapsed_s"]})


def main():
    pos = [a for a in sys.argv[1:] if not a.startswith("--")]
    n = int(pos[0]) if pos else 65536

    if "--probe-worker" in sys.argv:
        _probe_main()
        return
    if "--run-worker" in sys.argv:
        _run_main(n)
        return

    def spawn(argv):
        fd, path = tempfile.mkstemp(prefix="dpf_bench_", suffix=".log")
        child = subprocess.Popen(argv, stdout=fd, stderr=fd,
                                 start_new_session=True)
        os.close(fd)
        return child, path

    # Stage 1: relay probe in a detached child; abandon on timeout.
    probe, probe_log = spawn(
        [sys.executable, os.path.abspath(__file__), "--probe-worker"])
    rc = _wait_abandon(probe, PROBE_S)
    probe_ok = rc == 0 and "PROBE_OK" in open(probe_log).read()
    if rc is None:
        _result(0, n, {"error": "TPU relay unresponsive to a tiny probe "
                                "program after %ds (wedged); probe child "
                                "abandoned, not killed" % PROBE_S})
        sys.exit(2)
    if not probe_ok:
        _result(0, n, {"error": "TPU probe exited rc=%s without PROBE_OK"
                                % rc})
        sys.exit(2)

    # Stage 2: the measurement in a detached child; abandon on timeout.
    worker, run_log = spawn(
        [sys.executable, os.path.abspath(__file__), str(n), "--run-worker"])
    rc = _wait_abandon(worker, WATCHDOG_S)
    out = open(run_log).read().strip()
    line = next((ln for ln in reversed(out.splitlines())
                 if ln.startswith("{")), None)
    if rc == 0 and line:
        print(line, flush=True)
        return
    if rc is None:
        _result(0, n, {"error": "TPU backend unresponsive after %ds "
                                "(relay wedged mid-run?); measurement "
                                "child abandoned, not killed" % WATCHDOG_S})
        sys.exit(2)
    _result(0, n, {"error": "measurement worker exited rc=%s; tail: %s"
                            % (rc, out[-300:])})
    sys.exit(3)


if __name__ == "__main__":
    main()
