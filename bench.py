#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line with the headline metric.

Headline: server-side batched DPF evaluation throughput (dpfs/sec) at
entries=65536, entry_size=16, PRF=AES-128, batch=512 on one TPU chip —
the reference's V100 number for this config is 15,392 dpfs/sec
(README.md:130); vs_baseline = ours / V100.
"""

import json
import os
import sys
import threading

BASELINE_V100_AES128_65536 = 15392.0
WATCHDOG_S = int(os.environ.get("DPF_BENCH_WATCHDOG_S", "1500"))


def _run(n):
    import dpf_tpu
    from dpf_tpu.utils.bench import test_dpf_perf

    r = test_dpf_perf(N=n, batch=512, entrysize=16,
                      prf=dpf_tpu.PRF_AES128, reps=10, quiet=True,
                      check=True)
    print(json.dumps({
        "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, batch=512, "
                  "1 chip)" % n,
        "value": r["dpfs_per_sec"],
        "unit": "dpfs/sec",
        "vs_baseline": round(r["dpfs_per_sec"] / BASELINE_V100_AES128_65536,
                             4),
    }), flush=True)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    # The TPU relay in this environment can wedge (any first compile hangs
    # forever); a watchdog turns that into a diagnosable line instead of a
    # silent hang.  Worker failures are re-reported as an error line +
    # non-zero exit, never a silent success.
    failure = []

    def run_guarded():
        try:
            _run(n)
        except BaseException as e:  # noqa: BLE001 — reported below
            failure.append(e)

    worker = threading.Thread(target=run_guarded, daemon=True)
    worker.start()
    worker.join(WATCHDOG_S)
    if failure:
        print(json.dumps({
            "metric": "dpfs/sec (entries=%d)" % n,
            "value": 0,
            "unit": "dpfs/sec",
            "vs_baseline": 0.0,
            "error": "%s: %s" % (type(failure[0]).__name__,
                                 str(failure[0])[:300]),
        }), flush=True)
        os._exit(3)
    if worker.is_alive():
        print(json.dumps({
            "metric": "dpfs/sec (entries=%d, entry_size=16, AES128, "
                      "batch=512, 1 chip)" % n,
            "value": 0,
            "unit": "dpfs/sec",
            "vs_baseline": 0.0,
            "error": "TPU backend unresponsive after %ds (axon relay "
                     "wedged?)" % WATCHDOG_S,
        }), flush=True)
        os._exit(2)


if __name__ == "__main__":
    main()
